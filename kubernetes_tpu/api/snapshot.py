"""Snapshot -> fixed-shape device arrays.

Analog of the reference scheduler's cache snapshot (pkg/scheduler/backend/cache/
snapshot.go — UpdateSnapshot; NodeInfo in pkg/scheduler/framework/types.go): the
host-side cluster state is lowered once per scheduling step into padded, bucketed
arrays so the jitted kernels see static shapes (pad-and-bucket is the TPU answer
to pod/node churn — SURVEY.md §7 hard part 2).

Array schema (N nodes, P pending pods, R resources, L node-label literals,
T taint vocab, S node-selector terms, E exprs/term, TT terms/pod — all padded):

  node_valid[N]        bool   real node (padding rows are infeasible everywhere)
  node_alloc[N, R]     i32    allocatable, rescaled per-resource to fit int32
  node_used[N, R]      i32    sum of bound pods' requests (assume-cache output)
  node_unsched[N]      bool   spec.unschedulable
  node_labels[N, L]    f32    0/1 literal incidence (f32: matmul operand)
  node_taint_ns[N, T]  bool   NoSchedule/NoExecute taints (hard)
  node_taint_pref[N,T] bool   PreferNoSchedule taints (scored)
  pod_valid[P]         bool
  pod_req[P, R]        i32    effective pod request (+1 synthetic "pods" resource)
  pod_prio[P]          i32    spec.priority
  pod_tol_ns[P, T]     bool   True = pod tolerates hard taint t
  pod_tol_pref[P, T]   bool   True = pod tolerates PreferNoSchedule taint t
  pod_nodename[P]      i32    fixed node index, -1 unset, -2 named node missing
  pod_terms[P, TT]     i32    required node-selection term ids into sel_*, -1 pad
  pod_has_sel[P]       bool
  sel_mask[S, E, L]    f32    0/1 literal masks per term expression
  sel_kind[S, E]       i32    vocab.KIND_* per expression

Pending pods are pre-sorted into activeQ order — priority desc, then arrival
order (reference: pkg/scheduler/backend/queue/scheduling_queue.go — the default
queue sort plugin's Less) — so array index == commit order in ops/assign.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import types as t
from . import vocab as v

# Resources always present, in fixed axis order (extended resources appended).
_BASE_RESOURCES = (t.CPU, t.MEMORY, t.PODS, t.EPHEMERAL_STORAGE)
_DEFAULT_POD_LIMIT = 1_000_000  # allocatable "pods" when a node does not declare it
_INT32_MAX = 2**31 - 1


def _round_up_pow2(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _bucket(n: int, minimum: int = 8) -> int:
    """Pad-and-bucket size: powers of two up to 2048, then multiples of 2048.
    Bounds waste at scale (a 20k node axis pads to 20480, not 32768) while
    keeping the number of distinct compiled shapes small."""
    if n <= 2048:
        return _round_up_pow2(n, minimum)
    return ((n + 2047) // 2048) * 2048


@dataclass
class Snapshot:
    """Host-side cluster state handed to the encoder.

    `bound_pods` are pods with node_name set (running/assumed); they contribute
    node_used and (later layers) the existing-pod side of affinity/spread.
    """

    nodes: List[t.Node] = field(default_factory=list)
    pending_pods: List[t.Pod] = field(default_factory=list)
    bound_pods: List[t.Pod] = field(default_factory=list)
    pod_groups: Dict[str, t.PodGroup] = field(default_factory=dict)
    pvs: List[t.PersistentVolume] = field(default_factory=list)
    pvcs: Dict[str, t.PersistentVolumeClaim] = field(default_factory=dict)  # "ns/name" ->
    # storage.k8s.io StorageClasses by name (dynamic-provisioning feasibility)
    storage_classes: Dict[str, object] = field(default_factory=dict)
    # resource.k8s.io structured parameters: published device inventories and
    # the class selectors resolved against them (api/cluster.py types)
    resource_slices: List[object] = field(default_factory=list)
    device_classes: Dict[str, object] = field(default_factory=dict)


@dataclass
class EncodingMeta:
    """Host-side metadata needed to decode kernel outputs back to names.

    Under the delta path's superset reuse (api/delta.py — _wave_compatible),
    `resources`, `label_vocab` and `pairwise_vocab` may be strict SUPERSETS of
    what a fresh encode of the same snapshot would produce (surplus axes are
    inert).  Decisions are unaffected; consumers must not assume
    meta.resources == _resource_axis(snap) or compare metas across encoders —
    cross-backend comparisons should be decision-based (as the parity tests
    are)."""

    node_names: List[str]
    pod_names: List[str]  # in activeQ order == device pod index order
    pod_perm: np.ndarray  # pod_perm[device_pod_index] == pending_pods list index
    resources: List[str]
    resource_scale: np.ndarray  # i64[R]; device value * scale == canonical units
    label_vocab: v.LabelVocab
    taint_vocab: v.Interner
    pairwise_vocab: object  # api/pairwise.py — PairwiseVocab
    n_nodes: int
    n_pods: int
    # equivalence classes (the historical equivalence-cache analog, consumed
    # by ops/incremental.py — HoistCache): per-pod class index i32[P]
    # (class U = the bucketing padding class), the first pod row of each
    # class i64[U1], and the class count.  None on paths that do not build
    # them (the incremental device hoist then simply does not engage).
    pod_class: Optional[np.ndarray] = None
    class_first_pod: Optional[np.ndarray] = None
    n_classes: int = 0
    # node rows whose bound-pod contributions changed in THIS encode's sync
    # (api/delta.py — sync_bound); None = unknown (fresh rebuild).  The
    # HoistCache's authoritative dirty set is its own node_used row diff —
    # this is the encoder-side O(changes) report (spans, bench artifacts).
    dirty_nodes: Optional[np.ndarray] = None


@jax.tree_util.register_dataclass
@dataclass
class ClusterArrays:
    """The device-side snapshot (all numpy here; kernels move to device)."""

    node_valid: np.ndarray
    node_alloc: np.ndarray
    node_used: np.ndarray
    node_unsched: np.ndarray
    node_labels: np.ndarray
    node_taint_ns: np.ndarray
    node_taint_pref: np.ndarray
    pod_valid: np.ndarray
    pod_req: np.ndarray
    pod_prio: np.ndarray
    pod_tol_ns: np.ndarray
    pod_tol_pref: np.ndarray
    pod_nodename: np.ndarray
    pod_terms: np.ndarray
    pod_has_sel: np.ndarray
    sel_mask: np.ndarray
    sel_kind: np.ndarray
    # preferred (soft) node affinity: term ids into sel_* + weights
    pod_pref_terms: np.ndarray  # i32[P, PW], -1 pad
    pod_pref_weights: np.ndarray  # f32[P, PW]
    # pairwise-plugin state (api/pairwise.py): topology domains, interned
    # (selector, nsset, topoKey) terms, match matrices, initial counts
    node_dom: np.ndarray  # i32[K, N] domain id, D = key absent
    term_key: np.ndarray  # i32[T] -> topology key index
    m_pend: np.ndarray  # f32[T, P] pending pod matches term selector+ns
    # m_pend's nonzeros as per-pod slots (M = max matches over the wave):
    # the scan's symmetric-half reads/commits touch only these O(M) terms
    # per step instead of all T (ops/pairwise.py — interpod_required_ok)
    pod_match_terms: np.ndarray  # i32[P, M] matching term ids, -1 pad
    pod_match_vals: np.ndarray  # f32[P, M] match values (m_pend entries)
    pod_aff_self: np.ndarray  # bool[P, A1] pod matches its own required-affinity term
    term_counts0: np.ndarray  # f32[T, D+1] matching bound pods per domain
    anti_counts0: np.ndarray  # f32[T, D+1] bound pods OWNING anti term t
    pod_aff_terms: np.ndarray  # i32[P, A1] required pod-affinity term ids
    pod_anti_terms: np.ndarray  # i32[P, A2] required pod-anti-affinity term ids
    pod_pref_aff_terms: np.ndarray  # i32[P, B] preferred (anti-)affinity term ids
    pod_pref_aff_w: np.ndarray  # f32[P, B] signed weights (anti = negative)
    pref_own0: np.ndarray  # f32[T, D+1] weight-sums of bound pods owning pref terms
    pod_spread_terms: np.ndarray  # i32[P, C] topology-spread term ids
    pod_spread_maxskew: np.ndarray  # i32[P, C]
    pod_spread_hard: np.ndarray  # bool[P, C] DoNotSchedule?
    pod_ports: np.ndarray  # bool[P, PT] requested host ports
    node_ports0: np.ndarray  # bool[N, PT] ports taken by bound pods
    # gang scheduling (BASELINE config 5; analog of the coscheduling PodGroup)
    pod_group: np.ndarray  # i32[P] group index or -1
    group_min: np.ndarray  # i32[G] minMember per group
    # ImageLocality static score matrix (f32[P, N]; [P, 1] zeros when no
    # images anywhere — computed once at encode time, consumed verbatim by
    # every backend so parity is structural)
    image_score: np.ndarray

    @property
    def N(self) -> int:
        return self.node_alloc.shape[0]

    @property
    def P(self) -> int:
        return self.pod_req.shape[0]

    @property
    def R(self) -> int:
        return self.node_alloc.shape[1]


def _resource_axis(snap: Snapshot) -> List[str]:
    res = list(_BASE_RESOURCES)
    seen = set(res)
    for obj in [*snap.nodes]:
        for k in obj.allocatable:
            if k not in seen:
                seen.add(k)
                res.append(k)
    for pod in [*snap.pending_pods, *snap.bound_pods]:
        for k in pod.requests:
            if k not in seen:
                seen.add(k)
                res.append(k)
    return res


def _scale_for(values) -> int:
    """Exact-where-possible int32 rescale: gcd unit, widened if the max still
    overflows (widening rounds requests up / allocatable down — conservative).
    Accepts any int sequence or int64 ndarray; the single shared implementation
    keeps the encoder, the oracle, and the native mirror bit-identical."""
    nz = np.abs(np.asarray(values, dtype=np.int64).ravel())
    nz = nz[nz != 0]
    if nz.size == 0:
        return 1
    scale = max(1, int(np.gcd.reduce(nz)))
    m = int(nz.max())
    while m // scale > _INT32_MAX:
        scale *= 2
    return scale


def pod_effective_requests(pod: t.Pod, resources: Sequence[str]) -> List[int]:
    """Pod-level request vector; every pod consumes 1 of the synthetic "pods"
    resource (reference: noderesources/fit.go — computePodResourceRequest +
    the NodeInfo pod-count check)."""
    return [pod.requests.get(r, 0) if r != t.PODS else max(1, pod.requests.get(r, 1)) for r in resources]


def activeq_order(pods: Sequence[t.Pod]) -> np.ndarray:
    """Indices sorting pods into activeQ pop order: priority desc, arrival asc
    (reference: queue sort plugin — PrioritySort.Less).  Stable argsort on
    -priority keeps arrival order within a priority band."""
    prio = np.fromiter(
        (p.priority for p in pods), dtype=np.int64, count=len(pods)
    )
    return np.argsort(-prio, kind="stable")


_IMG_MIN_MB = 23.0  # imagelocality/image_locality.go — minThreshold (23 MB)
_IMG_MAX_MB = 1000.0  # maxThreshold


def image_score_value(sum_mb: float) -> np.float32:
    """ImageLocality score from summed present-image megabytes (f32,
    mirrored by the oracle): 100 * (clip(sum) - min) / (max - min).
    Rounded onto the bf16 score lattice (ops/bitplane.py) — the oracle
    calls this too, so both sides quantize identically under
    KTPU_SCORE_DTYPE."""
    from ..ops.bitplane import bf16_round_np

    s = np.float32(min(max(float(sum_mb), _IMG_MIN_MB), _IMG_MAX_MB))
    return np.float32(bf16_round_np(
        (s - np.float32(_IMG_MIN_MB))
        * np.float32(100.0)
        / np.float32(_IMG_MAX_MB - _IMG_MIN_MB)
    ))


def _image_score_matrix(nodes, reps, inv, N: int, P: int) -> np.ndarray:
    """f32[P, N] ImageLocality scores, or f32[P, 1] zeros when irrelevant.

    Image sizes quantize to whole MB so sums are integer-exact in f32 across
    numpy/XLA/C++ (reference computes in int64; imagelocality/image_locality.go
    — calculatePriority, sumImageScores without the spread factor — deviation
    documented in PARITY.md).  `reps`/`inv` are the spec-interned unique
    pending-pod specs and each sorted pod's spec index: the matmul runs over
    unique specs and rows are gathered per pod."""
    from ..ops.bitplane import np_score_dtype

    img_ids: Dict[str, int] = {}
    for pod in reps:
        for im in pod.images:
            img_ids.setdefault(im, len(img_ids))
    if not img_ids or not any(nd.images for nd in nodes):
        return np.zeros((P, 1), dtype=np_score_dtype())
    I = len(img_ids)
    node_mb = np.zeros((N, I), dtype=np.float32)
    for i, nd in enumerate(nodes):
        for im, size in nd.images.items():
            j = img_ids.get(im)
            if j is not None:
                node_mb[i, j] = np.float32(size // (1024 * 1024))
    pod_has = np.zeros((len(reps), I), dtype=np.float32)
    for k, pod in enumerate(reps):
        for im in pod.images:
            pod_has[k, img_ids[im]] = 1.0
    raw = pod_has @ node_mb.T  # integer-valued f32 MB sums, [U, N]
    s = np.clip(raw, _IMG_MIN_MB, _IMG_MAX_MB).astype(np.float32)
    scored = (
        (s - np.float32(_IMG_MIN_MB))
        * np.float32(100.0)
        / np.float32(_IMG_MAX_MB - _IMG_MIN_MB)
    ).astype(np.float32)
    from ..ops.bitplane import quantize_scores_np

    # stored on the bf16 score lattice (halved transfer + resident bytes;
    # the same round-to-nearest-even lattice image_score_value applies, so
    # the oracle mirror and this matrix agree bit-for-bit)
    scored = quantize_scores_np(scored)
    out = np.zeros((P, N), dtype=scored.dtype)  # zero == the empty-image score
    if len(inv):
        out[: len(inv)] = scored[inv]
    return out


def _pod_spec_key(pod: t.Pod) -> Tuple:
    """Encoding-relevant identity of a (volume-resolved) pending pod: pods from
    one workload template collapse to one key, so the encoder does per-spec
    work once and scatters rows (the host-side analog of keeping the MXU fed
    with batched work instead of scalar loops)."""
    return (
        tuple(sorted(pod.requests.items())),
        tuple(sorted(pod.labels.items())),
        pod.namespace,
        pod.node_name,
        pod.priority,
        pod.tolerations,
        pod.node_selector,
        pod.affinity,
        pod.topology_spread,
        pod.host_ports,
        pod.scheduling_gates,
        pod.pod_group,
        pod.images,
    )


def _identity_key(pod: t.Pod) -> Tuple:
    """Field-object identity profile of a pod: pods copied from one template
    (copy/replace) share these objects, so equal keys imply equal
    `_pod_spec_key` — the fast first level of the two-level interning.  MUST
    cover every field _pod_spec_key reads (one shared helper so the delta
    encoder's resident cache and group_by_spec cannot drift)."""
    return (
        id(pod.requests), id(pod.labels), pod.namespace, pod.node_name,
        pod.priority, id(pod.tolerations), id(pod.node_selector),
        id(pod.affinity), id(pod.topology_spread), id(pod.host_ports),
        id(pod.scheduling_gates), pod.pod_group, id(pod.images),
    )


class SpecInterner:
    """A PERSISTENT two-level interner: identity-profile -> canonical spec
    key survives across calls (successive waves stamped from the same objects
    share field objects), so steady-state group_by_spec costs O(P) dict hits
    instead of O(P) sorted() canonicalizations.  Used by the delta encoder
    and the sidecar client's wave interning.  Values keep the keyed pod alive
    so recycled ids can never alias a live entry.

    The identity-profile pass runs in C when the native helper builds
    (native/interner.c, ~0.5us/pod vs ~4us for the Python loop at 50k pods);
    grouping is bit-identical on either path and
    tests/test_snapshot.py::test_interner_native_matches_python pins that."""

    def __init__(self):
        self._keys: Dict[Tuple, Tuple] = {}
        from ..native import pyintern

        self._lib = pyintern.load()
        if self._lib is not None:
            self._h = self._lib.interner_new()
            if not self._h:
                self._lib = None
        if self._lib is not None:
            self._canon: Dict[Tuple, int] = {}  # spec key -> persistent kid
            self._key_by_kid: List[Tuple] = []

    def __del__(self):  # release the C table's pod pins
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_h", None):
            try:
                lib.interner_free(self._h)
            except Exception:
                pass
            self._h = None

    def group(self, pods: Sequence[t.Pod]):
        """-> (reps, inv, rep_keys) — same reps/inv as group_by_spec."""
        if self._lib is not None:
            return self._group_native(pods)
        if len(self._keys) > 2 * (len(pods) + 1024):
            self._keys.clear()
        cache = self._keys
        can_ids: Dict[Tuple, int] = {}
        reps: List[t.Pod] = []
        rep_keys: List[Tuple] = []
        inv = np.empty(len(pods), dtype=np.int64)
        for i, pod in enumerate(pods):
            ik = _identity_key(pod)
            ent = cache.get(ik)
            if ent is None:
                ent = (_pod_spec_key(pod), pod)
                cache[ik] = ent
            k = ent[0]
            su = can_ids.get(k)
            if su is None:
                su = len(reps)
                can_ids[k] = su
                reps.append(pod)
                rep_keys.append(k)
            inv[i] = su
        return reps, inv, tuple(rep_keys)

    def _group_native(self, pods: Sequence[t.Pod]):
        lib = self._lib
        if not isinstance(pods, list):
            pods = list(pods)
        n = len(pods)
        if int(lib.interner_prov(self._h)) != 0:
            # a prior batch left unresolved provisional entries — either its
            # slow path raised, or a pod's profile fields are not
            # identity-stable (property-backed attributes).  One occurrence
            # triggers a crash-only table wipe inside interner_lookup; if it
            # keeps happening the C fast path cannot help this workload, so
            # hand the instance to the Python loop for good.  Counted
            # SEPARATELY from the forced-miss latch below: a clean-batch
            # reset of the forced counter must not erase provisional
            # strikes (provisional leftovers typically coincide with zero
            # forced misses, so a shared counter could never latch).
            self._thrash_prov = getattr(self, "_thrash_prov", 0) + 1
            if self._thrash_prov >= 3:
                self._lib = None
                return self.group(pods)
        else:
            # same isolated-events rule as the forced latch: a batch with no
            # provisional leftovers resets the provisional streak, so three
            # transient slow-path failures weeks apart never permanently
            # disable the fast path — only PERSISTENT thrash latches
            self._thrash_prov = 0
        # same bounded-memory policy as the Python path's _keys.clear():
        # drop the profile table AND the spec-key registry together (C
        # entries hold kid indices into _key_by_kid, so they must reset as
        # one unit); kids restart from 0 afterwards
        if int(lib.interner_count(self._h)) > 2 * (n + 1024) or len(
            self._key_by_kid
        ) > 2 * (n + 1024):
            lib.interner_clear(self._h)
            self._canon.clear()
            self._key_by_kid.clear()
        keyid = np.empty(n, dtype=np.int64)
        miss = np.empty(n, dtype=np.int64)
        # NOTE: PyDLL checks the Python error flag after each call and
        # raises the pending exception itself, so no failure branches here
        n_miss = int(
            lib.interner_lookup(
                self._h, pods, keyid.ctypes.data, miss.ctypes.data
            )
        )
        if int(lib.interner_forced(self._h)) != 0:
            # identity-unstable pods (property/slots-backed profile fields)
            # bypass the pointer table entirely — forced misses resolve
            # correctly through the value slow path below, but with no
            # intra-batch dedup; if they keep appearing the C fast path
            # cannot help this workload, so latch onto the Python loop
            # (own counter — see the provisional latch above)
            self._thrash_forced = getattr(self, "_thrash_forced", 0) + 1
            if self._thrash_forced >= 3:
                self._lib = None
        else:
            # a clean batch resets the FORCED streak only: the latch is for
            # workloads that are PERSISTENTLY identity-unstable, not for one
            # odd pod ever — 3 isolated events weeks apart must not disable
            # the fast path for the process lifetime.  Provisional strikes
            # stay: their batches report zero forced misses by nature.
            self._thrash_forced = 0
        if n_miss:
            # miss holds only UNIQUE missing profiles (intra-batch
            # duplicates were resolved to provisional markers by the C
            # pass), so the sorted-canonicalization slow path runs once per
            # distinct spec, not once per pod
            canon = self._canon
            kids = np.empty(n_miss, dtype=np.int64)
            for k in range(n_miss):
                i = int(miss[k])
                key = _pod_spec_key(pods[i])
                kid = canon.get(key)
                if kid is None:
                    kid = len(self._key_by_kid)
                    canon[key] = kid
                    self._key_by_kid.append(key)
                kids[k] = kid
            lib.interner_insert(
                self._h, pods, miss.ctypes.data, kids.ctypes.data, n_miss
            )
            # resolve provisional markers -(m)-2 -> kids[m]
            neg = keyid < -1
            keyid[neg] = kids[-keyid[neg] - 2]
        percall = np.full(len(self._key_by_kid), -1, dtype=np.int64)
        inv = np.empty(n, dtype=np.int64)
        rep_idx = np.empty(n, dtype=np.int64)
        n_reps = int(
            lib.interner_canonicalize(
                keyid.ctypes.data, n, percall.ctypes.data,
                inv.ctypes.data, rep_idx.ctypes.data,
            )
        )
        reps = [pods[int(j)] for j in rep_idx[:n_reps]]
        rep_keys = tuple(
            self._key_by_kid[int(keyid[int(j)])] for j in rep_idx[:n_reps]
        )
        return reps, inv, rep_keys


def group_by_spec(pods: Sequence[t.Pod]) -> Tuple[List[t.Pod], np.ndarray]:
    """-> (reps, inv): unique encoding specs in first-occurrence order and each
    pod's spec index.  Interner-order equivalence: because every vocab below
    dedups on intern, processing unique specs in first-occurrence order assigns
    ids identical to the old per-pod loops (bit-identical arrays).

    Two-level interning: pods copied from a shared spec (copy.copy /
    dataclasses.replace — e.g. the sidecar's wire-interned waves) SHARE their
    field objects, so an identity-tuple fast path dedups them without sorting
    dicts; only one pod per identity profile pays the canonical
    `_pod_spec_key`.  Distinct-identity/equal-content profiles merge at the
    canonical level, so reps order and inv are exactly what the one-level
    loop produced (bit-identical arrays either way).  Workloads whose pods
    own distinct field objects (identity never hits) would pay the tuple
    overhead for nothing, so the fast path self-disables when its hit rate
    over the first window is poor."""
    id_ids: Dict[Tuple, int] = {}
    can_ids: Dict[Tuple, int] = {}
    id_to_spec: List[int] = []
    reps: List[t.Pod] = []
    inv = np.empty(len(pods), dtype=np.int64)
    use_fast = len(pods) > 512
    for i, pod in enumerate(pods):
        if use_fast:
            ik = _identity_key(pod)
            u = id_ids.get(ik)
            if u is not None:
                inv[i] = id_to_spec[u]
                continue
            if i == 1024 and len(id_ids) > 768:
                use_fast = False  # identity never hits: stop paying for it
            else:
                id_ids[ik] = len(id_to_spec)
        k = _pod_spec_key(pod)
        su = can_ids.get(k)
        if su is None:
            su = len(reps)
            can_ids[k] = su
            reps.append(pod)
        if use_fast:
            id_to_spec.append(su)
        inv[i] = su
    return reps, inv


def _node_taints(nd: t.Node) -> List[t.Taint]:
    # spec.unschedulable is modeled as the synthetic taint the reference's node
    # controller applies (node.kubernetes.io/unschedulable:NoSchedule), which makes
    # the NodeUnschedulable plugin's toleration-aware check fall out of the taint
    # kernel (reference: nodeunschedulable/node_unschedulable.go — Filter).
    ts = list(nd.taints)
    if nd.unschedulable:
        ts.append(t.Taint(key="node.kubernetes.io/unschedulable", effect=t.NO_SCHEDULE))
    return ts


def encode_snapshot(
    snap: Snapshot, *, bucket: bool = True, hard_pod_affinity_weight: float = 1.0
) -> Tuple[ClusterArrays, EncodingMeta]:
    """One-shot encode: a DeltaEncoder used for a single cycle (delta.py owns
    the staged implementation, so the incremental watch-driven path and this
    full path are one code body and cannot drift)."""
    from .delta import DeltaEncoder

    return DeltaEncoder(
        bucket=bucket, hard_pod_affinity_weight=hard_pod_affinity_weight
    ).encode(snap)
