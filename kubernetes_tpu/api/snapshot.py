"""Snapshot -> fixed-shape device arrays.

Analog of the reference scheduler's cache snapshot (pkg/scheduler/backend/cache/
snapshot.go — UpdateSnapshot; NodeInfo in pkg/scheduler/framework/types.go): the
host-side cluster state is lowered once per scheduling step into padded, bucketed
arrays so the jitted kernels see static shapes (pad-and-bucket is the TPU answer
to pod/node churn — SURVEY.md §7 hard part 2).

Array schema (N nodes, P pending pods, R resources, L node-label literals,
T taint vocab, S node-selector terms, E exprs/term, TT terms/pod — all padded):

  node_valid[N]        bool   real node (padding rows are infeasible everywhere)
  node_alloc[N, R]     i32    allocatable, rescaled per-resource to fit int32
  node_used[N, R]      i32    sum of bound pods' requests (assume-cache output)
  node_unsched[N]      bool   spec.unschedulable
  node_labels[N, L]    f32    0/1 literal incidence (f32: matmul operand)
  node_taint_ns[N, T]  bool   NoSchedule/NoExecute taints (hard)
  node_taint_pref[N,T] bool   PreferNoSchedule taints (scored)
  pod_valid[P]         bool
  pod_req[P, R]        i32    effective pod request (+1 synthetic "pods" resource)
  pod_prio[P]          i32    spec.priority
  pod_tol_ns[P, T]     bool   True = pod tolerates hard taint t
  pod_tol_pref[P, T]   bool   True = pod tolerates PreferNoSchedule taint t
  pod_nodename[P]      i32    fixed node index, -1 unset, -2 named node missing
  pod_terms[P, TT]     i32    required node-selection term ids into sel_*, -1 pad
  pod_has_sel[P]       bool
  sel_mask[S, E, L]    f32    0/1 literal masks per term expression
  sel_kind[S, E]       i32    vocab.KIND_* per expression

Pending pods are pre-sorted into activeQ order — priority desc, then arrival
order (reference: pkg/scheduler/backend/queue/scheduling_queue.go — the default
queue sort plugin's Less) — so array index == commit order in ops/assign.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import types as t
from . import vocab as v

# Resources always present, in fixed axis order (extended resources appended).
_BASE_RESOURCES = (t.CPU, t.MEMORY, t.PODS, t.EPHEMERAL_STORAGE)
_DEFAULT_POD_LIMIT = 1_000_000  # allocatable "pods" when a node does not declare it
_INT32_MAX = 2**31 - 1


def _round_up_pow2(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _bucket(n: int, minimum: int = 8) -> int:
    """Pad-and-bucket size: powers of two up to 2048, then multiples of 2048.
    Bounds waste at scale (a 20k node axis pads to 20480, not 32768) while
    keeping the number of distinct compiled shapes small."""
    if n <= 2048:
        return _round_up_pow2(n, minimum)
    return ((n + 2047) // 2048) * 2048


@dataclass
class Snapshot:
    """Host-side cluster state handed to the encoder.

    `bound_pods` are pods with node_name set (running/assumed); they contribute
    node_used and (later layers) the existing-pod side of affinity/spread.
    """

    nodes: List[t.Node] = field(default_factory=list)
    pending_pods: List[t.Pod] = field(default_factory=list)
    bound_pods: List[t.Pod] = field(default_factory=list)
    pod_groups: Dict[str, t.PodGroup] = field(default_factory=dict)
    pvs: List[t.PersistentVolume] = field(default_factory=list)
    pvcs: Dict[str, t.PersistentVolumeClaim] = field(default_factory=dict)  # "ns/name" ->
    # storage.k8s.io StorageClasses by name (dynamic-provisioning feasibility)
    storage_classes: Dict[str, object] = field(default_factory=dict)
    # resource.k8s.io structured parameters: published device inventories and
    # the class selectors resolved against them (api/cluster.py types)
    resource_slices: List[object] = field(default_factory=list)
    device_classes: Dict[str, object] = field(default_factory=dict)


@dataclass
class EncodingMeta:
    """Host-side metadata needed to decode kernel outputs back to names."""

    node_names: List[str]
    pod_names: List[str]  # in activeQ order == device pod index order
    pod_perm: np.ndarray  # pod_perm[device_pod_index] == pending_pods list index
    resources: List[str]
    resource_scale: np.ndarray  # i64[R]; device value * scale == canonical units
    label_vocab: v.LabelVocab
    taint_vocab: v.Interner
    pairwise_vocab: object  # api/pairwise.py — PairwiseVocab
    n_nodes: int
    n_pods: int


@jax.tree_util.register_dataclass
@dataclass
class ClusterArrays:
    """The device-side snapshot (all numpy here; kernels move to device)."""

    node_valid: np.ndarray
    node_alloc: np.ndarray
    node_used: np.ndarray
    node_unsched: np.ndarray
    node_labels: np.ndarray
    node_taint_ns: np.ndarray
    node_taint_pref: np.ndarray
    pod_valid: np.ndarray
    pod_req: np.ndarray
    pod_prio: np.ndarray
    pod_tol_ns: np.ndarray
    pod_tol_pref: np.ndarray
    pod_nodename: np.ndarray
    pod_terms: np.ndarray
    pod_has_sel: np.ndarray
    sel_mask: np.ndarray
    sel_kind: np.ndarray
    # preferred (soft) node affinity: term ids into sel_* + weights
    pod_pref_terms: np.ndarray  # i32[P, PW], -1 pad
    pod_pref_weights: np.ndarray  # f32[P, PW]
    # pairwise-plugin state (api/pairwise.py): topology domains, interned
    # (selector, nsset, topoKey) terms, match matrices, initial counts
    node_dom: np.ndarray  # i32[K, N] domain id, D = key absent
    term_key: np.ndarray  # i32[T] -> topology key index
    m_pend: np.ndarray  # f32[T, P] pending pod matches term selector+ns
    term_counts0: np.ndarray  # f32[T, D+1] matching bound pods per domain
    anti_counts0: np.ndarray  # f32[T, D+1] bound pods OWNING anti term t
    pod_aff_terms: np.ndarray  # i32[P, A1] required pod-affinity term ids
    pod_anti_terms: np.ndarray  # i32[P, A2] required pod-anti-affinity term ids
    pod_pref_aff_terms: np.ndarray  # i32[P, B] preferred (anti-)affinity term ids
    pod_pref_aff_w: np.ndarray  # f32[P, B] signed weights (anti = negative)
    pref_own0: np.ndarray  # f32[T, D+1] weight-sums of bound pods owning pref terms
    pod_spread_terms: np.ndarray  # i32[P, C] topology-spread term ids
    pod_spread_maxskew: np.ndarray  # i32[P, C]
    pod_spread_hard: np.ndarray  # bool[P, C] DoNotSchedule?
    pod_ports: np.ndarray  # bool[P, PT] requested host ports
    node_ports0: np.ndarray  # bool[N, PT] ports taken by bound pods
    # gang scheduling (BASELINE config 5; analog of the coscheduling PodGroup)
    pod_group: np.ndarray  # i32[P] group index or -1
    group_min: np.ndarray  # i32[G] minMember per group
    # ImageLocality static score matrix (f32[P, N]; [P, 1] zeros when no
    # images anywhere — computed once at encode time, consumed verbatim by
    # every backend so parity is structural)
    image_score: np.ndarray

    @property
    def N(self) -> int:
        return self.node_alloc.shape[0]

    @property
    def P(self) -> int:
        return self.pod_req.shape[0]

    @property
    def R(self) -> int:
        return self.node_alloc.shape[1]


def _resource_axis(snap: Snapshot) -> List[str]:
    res = list(_BASE_RESOURCES)
    seen = set(res)
    for obj in [*snap.nodes]:
        for k in obj.allocatable:
            if k not in seen:
                seen.add(k)
                res.append(k)
    for pod in [*snap.pending_pods, *snap.bound_pods]:
        for k in pod.requests:
            if k not in seen:
                seen.add(k)
                res.append(k)
    return res


def _scale_for(values) -> int:
    """Exact-where-possible int32 rescale: gcd unit, widened if the max still
    overflows (widening rounds requests up / allocatable down — conservative).
    Accepts any int sequence or int64 ndarray; the single shared implementation
    keeps the encoder, the oracle, and the native mirror bit-identical."""
    nz = np.abs(np.asarray(values, dtype=np.int64).ravel())
    nz = nz[nz != 0]
    if nz.size == 0:
        return 1
    scale = max(1, int(np.gcd.reduce(nz)))
    m = int(nz.max())
    while m // scale > _INT32_MAX:
        scale *= 2
    return scale


def pod_effective_requests(pod: t.Pod, resources: Sequence[str]) -> List[int]:
    """Pod-level request vector; every pod consumes 1 of the synthetic "pods"
    resource (reference: noderesources/fit.go — computePodResourceRequest +
    the NodeInfo pod-count check)."""
    return [pod.requests.get(r, 0) if r != t.PODS else max(1, pod.requests.get(r, 1)) for r in resources]


def activeq_order(pods: Sequence[t.Pod]) -> np.ndarray:
    """Indices sorting pods into activeQ pop order: priority desc, arrival asc
    (reference: queue sort plugin — PrioritySort.Less)."""
    return np.array(
        sorted(range(len(pods)), key=lambda i: (-pods[i].priority, i)), dtype=np.int64
    )


_IMG_MIN_MB = 23.0  # imagelocality/image_locality.go — minThreshold (23 MB)
_IMG_MAX_MB = 1000.0  # maxThreshold


def image_score_value(sum_mb: float) -> np.float32:
    """ImageLocality score from summed present-image megabytes (f32,
    mirrored by the oracle): 100 * (clip(sum) - min) / (max - min)."""
    s = np.float32(min(max(float(sum_mb), _IMG_MIN_MB), _IMG_MAX_MB))
    return np.float32(
        (s - np.float32(_IMG_MIN_MB))
        * np.float32(100.0)
        / np.float32(_IMG_MAX_MB - _IMG_MIN_MB)
    )


def _image_score_matrix(nodes, reps, inv, N: int, P: int) -> np.ndarray:
    """f32[P, N] ImageLocality scores, or f32[P, 1] zeros when irrelevant.

    Image sizes quantize to whole MB so sums are integer-exact in f32 across
    numpy/XLA/C++ (reference computes in int64; imagelocality/image_locality.go
    — calculatePriority, sumImageScores without the spread factor — deviation
    documented in PARITY.md).  `reps`/`inv` are the spec-interned unique
    pending-pod specs and each sorted pod's spec index: the matmul runs over
    unique specs and rows are gathered per pod."""
    img_ids: Dict[str, int] = {}
    for pod in reps:
        for im in pod.images:
            img_ids.setdefault(im, len(img_ids))
    if not img_ids or not any(nd.images for nd in nodes):
        return np.zeros((P, 1), dtype=np.float32)
    I = len(img_ids)
    node_mb = np.zeros((N, I), dtype=np.float32)
    for i, nd in enumerate(nodes):
        for im, size in nd.images.items():
            j = img_ids.get(im)
            if j is not None:
                node_mb[i, j] = np.float32(size // (1024 * 1024))
    pod_has = np.zeros((len(reps), I), dtype=np.float32)
    for k, pod in enumerate(reps):
        for im in pod.images:
            pod_has[k, img_ids[im]] = 1.0
    raw = pod_has @ node_mb.T  # integer-valued f32 MB sums, [U, N]
    s = np.clip(raw, _IMG_MIN_MB, _IMG_MAX_MB).astype(np.float32)
    scored = (
        (s - np.float32(_IMG_MIN_MB))
        * np.float32(100.0)
        / np.float32(_IMG_MAX_MB - _IMG_MIN_MB)
    ).astype(np.float32)
    out = np.zeros((P, N), dtype=np.float32)  # zero == the empty-image score
    if len(inv):
        out[: len(inv)] = scored[inv]
    return out


def _pod_spec_key(pod: t.Pod) -> Tuple:
    """Encoding-relevant identity of a (volume-resolved) pending pod: pods from
    one workload template collapse to one key, so the encoder does per-spec
    work once and scatters rows (the host-side analog of keeping the MXU fed
    with batched work instead of scalar loops)."""
    return (
        tuple(sorted(pod.requests.items())),
        tuple(sorted(pod.labels.items())),
        pod.namespace,
        pod.node_name,
        pod.priority,
        pod.tolerations,
        pod.node_selector,
        pod.affinity,
        pod.topology_spread,
        pod.host_ports,
        pod.scheduling_gates,
        pod.pod_group,
        pod.images,
    )


def group_by_spec(pods: Sequence[t.Pod]) -> Tuple[List[t.Pod], np.ndarray]:
    """-> (reps, inv): unique encoding specs in first-occurrence order and each
    pod's spec index.  Interner-order equivalence: because every vocab below
    dedups on intern, processing unique specs in first-occurrence order assigns
    ids identical to the old per-pod loops (bit-identical arrays)."""
    ids: Dict[Tuple, int] = {}
    reps: List[t.Pod] = []
    inv = np.empty(len(pods), dtype=np.int64)
    for i, pod in enumerate(pods):
        k = _pod_spec_key(pod)
        u = ids.get(k)
        if u is None:
            u = len(reps)
            ids[k] = u
            reps.append(pod)
        inv[i] = u
    return reps, inv


def _node_taints(nd: t.Node) -> List[t.Taint]:
    # spec.unschedulable is modeled as the synthetic taint the reference's node
    # controller applies (node.kubernetes.io/unschedulable:NoSchedule), which makes
    # the NodeUnschedulable plugin's toleration-aware check fall out of the taint
    # kernel (reference: nodeunschedulable/node_unschedulable.go — Filter).
    ts = list(nd.taints)
    if nd.unschedulable:
        ts.append(t.Taint(key="node.kubernetes.io/unschedulable", effect=t.NO_SCHEDULE))
    return ts


def encode_snapshot(
    snap: Snapshot, *, bucket: bool = True, hard_pod_affinity_weight: float = 1.0
) -> Tuple[ClusterArrays, EncodingMeta]:
    from .volumes import resolve_snapshot

    snap = resolve_snapshot(snap)
    nodes, pending = snap.nodes, snap.pending_pods
    n, p = len(nodes), len(pending)
    N = _bucket(n) if bucket else max(1, n)
    P = _bucket(p) if bucket else max(1, p)

    resources = _resource_axis(snap)
    R = len(resources)

    # Spec interning: pods stamped from one template share all
    # encoding-relevant fields, so every per-pod computation below runs once
    # per unique spec (U ≪ P for real workloads) and results scatter to pod
    # rows through `inv` — the encoder's Python cost stops scaling with the
    # wave size (SURVEY.md §7 hard part 4: the host must not be the bottleneck).
    perm = activeq_order(pending)
    sorted_pending = [pending[i] for i in perm]
    reps, inv = group_by_spec(sorted_pending)
    U = len(reps)

    # --- label vocab over node labels (selectors lower against this) ---
    # Only label KEYS referenced by some pod's nodeSelector / node-affinity
    # expression enter the literal vocab: unreferenced labels (notably the
    # per-node kubernetes.io/hostname) cannot influence any selector, and
    # would otherwise blow the L axis up to O(N).  Topology keys are interned
    # separately as domains (api/pairwise.py).
    referenced_keys = set()
    for pod in reps:
        for k, _ in pod.node_selector:
            referenced_keys.add(k)
        if pod.affinity:
            for term in pod.affinity.required_node_terms:
                for e in term.match_expressions:
                    referenced_keys.add(e.key)
            for pt in pod.affinity.preferred_node_terms:
                for e in pt.preference.match_expressions:
                    referenced_keys.add(e.key)
    # nodes intern by filtered-label profile (zone-style labels repeat across
    # the fleet; per-node hostname enters only when a pod references it)
    lab = v.LabelVocab()
    nlab_ids: Dict[Tuple, int] = {}
    nlab_rows: List[List[int]] = []
    nlab_inv = np.empty(n, dtype=np.int64)
    for i, nd in enumerate(nodes):
        # sorted key: two nodes with equal filtered label SETS share a profile
        # regardless of dict insertion order
        fk = tuple(sorted((k, val) for k, val in nd.labels.items() if k in referenced_keys))
        u = nlab_ids.get(fk)
        if u is None:
            u = len(nlab_rows)
            nlab_ids[fk] = u
            nlab_rows.append(lab.add_labels(dict(fk)))
        nlab_inv[i] = u

    # --- taint vocab (interned by node taint profile) ---
    taints = v.Interner()
    tprof_ids: Dict[Tuple, int] = {}
    tprof: List[List[t.Taint]] = []
    tinv = np.empty(n, dtype=np.int64)
    for i, nd in enumerate(nodes):
        key = (nd.taints, nd.unschedulable)
        u = tprof_ids.get(key)
        if u is None:
            u = len(tprof)
            tprof_ids[key] = u
            ts = _node_taints(nd)
            tprof.append(ts)
            for tn in ts:
                taints.intern((tn.key, tn.value, tn.effect))
        tinv[i] = u
    T = max(1, len(taints))

    # --- raw quantities, then per-resource rescale to int32 ---
    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    aprof_ids: Dict[Tuple, int] = {}
    arows: List[List[int]] = []
    ainv = np.empty(n, dtype=np.int64)
    for i, nd in enumerate(nodes):
        key = tuple(sorted(nd.allocatable.items()))
        u = aprof_ids.get(key)
        if u is None:
            u = len(arows)
            aprof_ids[key] = u
            arows.append(
                [
                    nd.allocatable.get(r, _DEFAULT_POD_LIMIT if r == t.PODS else 0)
                    for r in resources
                ]
            )
        ainv[i] = u
    alloc_uniq = (
        np.array(arows, dtype=np.int64) if arows else np.zeros((1, R), dtype=np.int64)
    )
    alloc_raw = alloc_uniq[ainv] if n else np.zeros((0, R), dtype=np.int64)

    req_uniq = (
        np.array([pod_effective_requests(rp, resources) for rp in reps], dtype=np.int64)
        if U
        else np.zeros((1, R), dtype=np.int64)
    )
    req_raw = req_uniq[inv] if p else np.zeros((0, R), dtype=np.int64)

    used_raw = np.zeros((n, R), dtype=np.int64)
    breq_ids: Dict[Tuple, int] = {}
    brows: List[List[int]] = []
    b_nodes: List[int] = []
    b_u: List[int] = []
    for bp in snap.bound_pods:
        i = node_index.get(bp.node_name)
        if i is None:
            continue
        key = tuple(sorted(bp.requests.items()))
        u = breq_ids.get(key)
        if u is None:
            u = len(brows)
            breq_ids[key] = u
            brows.append(pod_effective_requests(bp, resources))
        b_nodes.append(i)
        b_u.append(u)
    if b_nodes:
        np.add.at(
            used_raw,
            np.array(b_nodes, dtype=np.int64),
            np.array(brows, dtype=np.int64)[np.array(b_u, dtype=np.int64)],
        )

    # per-resource int32 rescale: gcd over unique values (duplicates cannot
    # change a gcd or max), vectorized
    scale = np.ones(R, dtype=np.int64)
    stacked = np.concatenate([alloc_uniq, req_uniq, used_raw], axis=0)
    for j in range(R):
        scale[j] = _scale_for(stacked[:, j])
    # ceil for demand, floor for supply when the unit is inexact (conservative)
    req_s = -(-req_raw // scale)
    used_s = -(-used_raw // scale)
    alloc_s = alloc_raw // scale

    node_alloc = np.zeros((N, R), dtype=np.int32)
    node_used = np.zeros((N, R), dtype=np.int32)
    node_alloc[:n] = alloc_s
    node_used[:n] = used_s

    node_valid = np.zeros(N, dtype=bool)
    node_valid[:n] = True
    node_unsched = np.zeros(N, dtype=bool)
    node_unsched[:n] = [nd.unschedulable for nd in nodes]

    L = max(1, len(lab))
    node_labels = np.zeros((N, L), dtype=np.float32)
    if n:
        lab_uniq = np.zeros((max(1, len(nlab_rows)), L), dtype=np.float32)
        for u, lits in enumerate(nlab_rows):
            lab_uniq[u, lits] = 1.0
        node_labels[:n] = lab_uniq[nlab_inv]

    node_taint_ns = np.zeros((N, T), dtype=bool)
    node_taint_pref = np.zeros((N, T), dtype=bool)
    if n:
        tns_uniq = np.zeros((max(1, len(tprof)), T), dtype=bool)
        tpref_uniq = np.zeros((max(1, len(tprof)), T), dtype=bool)
        for u, ts in enumerate(tprof):
            for tn in ts:
                tid = taints.get((tn.key, tn.value, tn.effect))
                if tn.effect == t.PREFER_NO_SCHEDULE:
                    tpref_uniq[u, tid] = True
                else:
                    tns_uniq[u, tid] = True
        node_taint_ns[:n] = tns_uniq[tinv]
        node_taint_pref[:n] = tpref_uniq[tinv]

    # --- pods (in activeQ order; all per-spec, scattered through inv) ---
    # SchedulingGates: gated pods never enter the schedulable set (reference:
    # schedulinggates/scheduling_gates.go — PreEnqueue holds them out of activeQ);
    # they come back with verdict -1 (still pending).
    pod_valid = np.zeros(P, dtype=bool)
    pod_req = np.zeros((P, R), dtype=np.int32)
    pod_req[:p] = req_s
    pod_prio = np.zeros(P, dtype=np.int32)
    pod_tol_ns = np.ones((P, T), dtype=bool)  # default: padding tolerates all
    pod_tol_pref = np.ones((P, T), dtype=bool)
    pod_nodename = np.full(P, -1, dtype=np.int32)

    table = v.TermTable()
    pod_term_lists: List[List[int]] = []
    pref_lists: List[List[Tuple[int, float]]] = []
    u_valid = np.empty(max(1, U), dtype=bool)
    u_prio = np.zeros(max(1, U), dtype=np.int32)
    u_tol_ns = np.ones((max(1, U), T), dtype=bool)
    u_tol_pref = np.ones((max(1, U), T), dtype=bool)
    u_nodename = np.full(max(1, U), -1, dtype=np.int32)
    taint_objs = [t.Taint(tk, tv, te) for (tk, tv, te) in taints.items]
    # a taint's effect class is a property of the vocab, not the pod: each
    # tol row only masks its own effect class (the other stays default-True)
    taint_is_pref = np.array(
        [tn.effect == t.PREFER_NO_SCHEDULE for tn in taint_objs], dtype=bool
    )
    for ui, pod in enumerate(reps):
        u_valid[ui] = not pod.scheduling_gates
        u_prio[ui] = pod.priority
        if pod.tolerations:
            for tid, taint in enumerate(taint_objs):
                tol = any(tol.tolerates(taint) for tol in pod.tolerations)
                if taint.effect == t.PREFER_NO_SCHEDULE:
                    u_tol_pref[ui, tid] = tol
                else:
                    u_tol_ns[ui, tid] = tol
        elif taint_objs:
            u_tol_ns[ui] = taint_is_pref  # no tolerations: intolerant of every
            u_tol_pref[ui] = ~taint_is_pref  # taint in the row's effect class
        if pod.node_name:
            u_nodename[ui] = node_index.get(pod.node_name, -2)
        terms = v.pod_required_node_terms(pod, lab)
        pod_term_lists.append([] if terms is None else [table.intern(tm) for tm in terms])
        # preferred node affinity: weight per matching term (empty term matches
        # nothing, mirroring the required path)
        prefs: List[Tuple[int, float]] = []
        if pod.affinity:
            for pt in pod.affinity.preferred_node_terms:
                if pt.preference.match_expressions:
                    prefs.append(
                        (table.intern(v.lower_node_term(pt.preference.match_expressions, lab)), float(pt.weight))
                    )
        pref_lists.append(prefs)
    if p:
        pod_valid[:p] = u_valid[inv]
        pod_prio[:p] = u_prio[inv]
        pod_tol_ns[:p] = u_tol_ns[inv]
        pod_tol_pref[:p] = u_tol_pref[inv]
        pod_nodename[:p] = u_nodename[inv]

    TT = max(1, max((len(x) for x in pod_term_lists), default=1))
    u_terms = np.full((max(1, U), TT), -1, dtype=np.int32)
    u_has_sel = np.zeros(max(1, U), dtype=bool)
    for ui, ids in enumerate(pod_term_lists):
        if ids:
            u_has_sel[ui] = True
            u_terms[ui, : len(ids)] = ids
    pod_terms = np.full((P, TT), -1, dtype=np.int32)
    pod_has_sel = np.zeros(P, dtype=bool)
    if p:
        pod_terms[:p] = u_terms[inv]
        pod_has_sel[:p] = u_has_sel[inv]

    PW = max(1, max((len(x) for x in pref_lists), default=1))
    u_pref_terms = np.full((max(1, U), PW), -1, dtype=np.int32)
    u_pref_weights = np.zeros((max(1, U), PW), dtype=np.float32)
    for ui, prefs in enumerate(pref_lists):
        for a, (tid, w) in enumerate(prefs):
            u_pref_terms[ui, a] = tid
            u_pref_weights[ui, a] = w
    pod_pref_terms = np.full((P, PW), -1, dtype=np.int32)
    pod_pref_weights = np.zeros((P, PW), dtype=np.float32)
    if p:
        pod_pref_terms[:p] = u_pref_terms[inv]
        pod_pref_weights[:p] = u_pref_weights[inv]

    sel_mask, sel_kind = table.encode(L)

    # gang groups: pods referencing a PodGroup name share an index; minMember
    # defaults to the group's pod count when no PodGroup object is given
    group_ids = v.Interner()
    u_group = np.full(max(1, U), -1, dtype=np.int32)
    for ui, pod in enumerate(reps):
        if pod.pod_group:
            u_group[ui] = group_ids.intern(pod.pod_group)
    pod_group = np.full(P, -1, dtype=np.int32)
    if p:
        pod_group[:p] = u_group[inv]
    G = max(1, len(group_ids))
    group_min = np.ones(G, dtype=np.int32)
    if len(group_ids):
        counts = np.bincount(pod_group[pod_group >= 0], minlength=G)
        for gi, gname in enumerate(group_ids.items):
            pg = snap.pod_groups.get(gname)
            group_min[gi] = pg.min_member if pg else int(counts[gi])

    from .pairwise import build_pairwise

    _pair_voc, pair = build_pairwise(
        nodes, reps, snap.bound_pods, node_index, N, P,
        hard_pod_affinity_weight=hard_pod_affinity_weight,
        pending_inv=inv,
    )

    arrays = ClusterArrays(
        node_valid=node_valid,
        node_alloc=node_alloc,
        node_used=node_used,
        node_unsched=node_unsched,
        node_labels=node_labels,
        node_taint_ns=node_taint_ns,
        node_taint_pref=node_taint_pref,
        pod_valid=pod_valid,
        pod_req=pod_req,
        pod_prio=pod_prio,
        pod_tol_ns=pod_tol_ns,
        pod_tol_pref=pod_tol_pref,
        pod_nodename=pod_nodename,
        pod_terms=pod_terms,
        pod_has_sel=pod_has_sel,
        sel_mask=sel_mask,
        sel_kind=sel_kind,
        pod_pref_terms=pod_pref_terms,
        pod_pref_weights=pod_pref_weights,
        pod_group=pod_group,
        group_min=group_min,
        image_score=_image_score_matrix(nodes, reps, inv, N, P),
        **pair,
    )
    meta = EncodingMeta(
        node_names=[nd.name for nd in nodes],
        pod_names=[pending[i].name for i in perm],
        pod_perm=perm,
        resources=resources,
        resource_scale=scale,
        label_vocab=lab,
        taint_vocab=taints,
        pairwise_vocab=_pair_voc,
        n_nodes=n,
        n_pods=p,
    )
    return arrays, meta
