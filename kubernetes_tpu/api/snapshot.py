"""Snapshot -> fixed-shape device arrays.

Analog of the reference scheduler's cache snapshot (pkg/scheduler/backend/cache/
snapshot.go — UpdateSnapshot; NodeInfo in pkg/scheduler/framework/types.go): the
host-side cluster state is lowered once per scheduling step into padded, bucketed
arrays so the jitted kernels see static shapes (pad-and-bucket is the TPU answer
to pod/node churn — SURVEY.md §7 hard part 2).

Array schema (N nodes, P pending pods, R resources, L node-label literals,
T taint vocab, S node-selector terms, E exprs/term, TT terms/pod — all padded):

  node_valid[N]        bool   real node (padding rows are infeasible everywhere)
  node_alloc[N, R]     i32    allocatable, rescaled per-resource to fit int32
  node_used[N, R]      i32    sum of bound pods' requests (assume-cache output)
  node_unsched[N]      bool   spec.unschedulable
  node_labels[N, L]    f32    0/1 literal incidence (f32: matmul operand)
  node_taint_ns[N, T]  bool   NoSchedule/NoExecute taints (hard)
  node_taint_pref[N,T] bool   PreferNoSchedule taints (scored)
  pod_valid[P]         bool
  pod_req[P, R]        i32    effective pod request (+1 synthetic "pods" resource)
  pod_prio[P]          i32    spec.priority
  pod_tol_ns[P, T]     bool   True = pod tolerates hard taint t
  pod_tol_pref[P, T]   bool   True = pod tolerates PreferNoSchedule taint t
  pod_nodename[P]      i32    fixed node index, -1 unset, -2 named node missing
  pod_terms[P, TT]     i32    required node-selection term ids into sel_*, -1 pad
  pod_has_sel[P]       bool
  sel_mask[S, E, L]    f32    0/1 literal masks per term expression
  sel_kind[S, E]       i32    vocab.KIND_* per expression

Pending pods are pre-sorted into activeQ order — priority desc, then arrival
order (reference: pkg/scheduler/backend/queue/scheduling_queue.go — the default
queue sort plugin's Less) — so array index == commit order in ops/assign.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import types as t
from . import vocab as v

# Resources always present, in fixed axis order (extended resources appended).
_BASE_RESOURCES = (t.CPU, t.MEMORY, t.PODS, t.EPHEMERAL_STORAGE)
_DEFAULT_POD_LIMIT = 1_000_000  # allocatable "pods" when a node does not declare it
_INT32_MAX = 2**31 - 1


def _round_up_pow2(n: int, minimum: int = 8) -> int:
    return max(minimum, 1 << max(0, math.ceil(math.log2(max(1, n)))))


def _bucket(n: int, minimum: int = 8) -> int:
    """Pad-and-bucket size: powers of two up to 2048, then multiples of 2048.
    Bounds waste at scale (a 20k node axis pads to 20480, not 32768) while
    keeping the number of distinct compiled shapes small."""
    if n <= 2048:
        return _round_up_pow2(n, minimum)
    return ((n + 2047) // 2048) * 2048


@dataclass
class Snapshot:
    """Host-side cluster state handed to the encoder.

    `bound_pods` are pods with node_name set (running/assumed); they contribute
    node_used and (later layers) the existing-pod side of affinity/spread.
    """

    nodes: List[t.Node] = field(default_factory=list)
    pending_pods: List[t.Pod] = field(default_factory=list)
    bound_pods: List[t.Pod] = field(default_factory=list)
    pod_groups: Dict[str, t.PodGroup] = field(default_factory=dict)
    pvs: List[t.PersistentVolume] = field(default_factory=list)
    pvcs: Dict[str, t.PersistentVolumeClaim] = field(default_factory=dict)  # "ns/name" ->
    # storage.k8s.io StorageClasses by name (dynamic-provisioning feasibility)
    storage_classes: Dict[str, object] = field(default_factory=dict)
    # resource.k8s.io structured parameters: published device inventories and
    # the class selectors resolved against them (api/cluster.py types)
    resource_slices: List[object] = field(default_factory=list)
    device_classes: Dict[str, object] = field(default_factory=dict)


@dataclass
class EncodingMeta:
    """Host-side metadata needed to decode kernel outputs back to names."""

    node_names: List[str]
    pod_names: List[str]  # in activeQ order == device pod index order
    pod_perm: np.ndarray  # pod_perm[device_pod_index] == pending_pods list index
    resources: List[str]
    resource_scale: np.ndarray  # i64[R]; device value * scale == canonical units
    label_vocab: v.LabelVocab
    taint_vocab: v.Interner
    pairwise_vocab: object  # api/pairwise.py — PairwiseVocab
    n_nodes: int
    n_pods: int


@jax.tree_util.register_dataclass
@dataclass
class ClusterArrays:
    """The device-side snapshot (all numpy here; kernels move to device)."""

    node_valid: np.ndarray
    node_alloc: np.ndarray
    node_used: np.ndarray
    node_unsched: np.ndarray
    node_labels: np.ndarray
    node_taint_ns: np.ndarray
    node_taint_pref: np.ndarray
    pod_valid: np.ndarray
    pod_req: np.ndarray
    pod_prio: np.ndarray
    pod_tol_ns: np.ndarray
    pod_tol_pref: np.ndarray
    pod_nodename: np.ndarray
    pod_terms: np.ndarray
    pod_has_sel: np.ndarray
    sel_mask: np.ndarray
    sel_kind: np.ndarray
    # preferred (soft) node affinity: term ids into sel_* + weights
    pod_pref_terms: np.ndarray  # i32[P, PW], -1 pad
    pod_pref_weights: np.ndarray  # f32[P, PW]
    # pairwise-plugin state (api/pairwise.py): topology domains, interned
    # (selector, nsset, topoKey) terms, match matrices, initial counts
    node_dom: np.ndarray  # i32[K, N] domain id, D = key absent
    term_key: np.ndarray  # i32[T] -> topology key index
    m_pend: np.ndarray  # f32[T, P] pending pod matches term selector+ns
    term_counts0: np.ndarray  # f32[T, D+1] matching bound pods per domain
    anti_counts0: np.ndarray  # f32[T, D+1] bound pods OWNING anti term t
    pod_aff_terms: np.ndarray  # i32[P, A1] required pod-affinity term ids
    pod_anti_terms: np.ndarray  # i32[P, A2] required pod-anti-affinity term ids
    pod_pref_aff_terms: np.ndarray  # i32[P, B] preferred (anti-)affinity term ids
    pod_pref_aff_w: np.ndarray  # f32[P, B] signed weights (anti = negative)
    pref_own0: np.ndarray  # f32[T, D+1] weight-sums of bound pods owning pref terms
    pod_spread_terms: np.ndarray  # i32[P, C] topology-spread term ids
    pod_spread_maxskew: np.ndarray  # i32[P, C]
    pod_spread_hard: np.ndarray  # bool[P, C] DoNotSchedule?
    pod_ports: np.ndarray  # bool[P, PT] requested host ports
    node_ports0: np.ndarray  # bool[N, PT] ports taken by bound pods
    # gang scheduling (BASELINE config 5; analog of the coscheduling PodGroup)
    pod_group: np.ndarray  # i32[P] group index or -1
    group_min: np.ndarray  # i32[G] minMember per group
    # ImageLocality static score matrix (f32[P, N]; [P, 1] zeros when no
    # images anywhere — computed once at encode time, consumed verbatim by
    # every backend so parity is structural)
    image_score: np.ndarray

    @property
    def N(self) -> int:
        return self.node_alloc.shape[0]

    @property
    def P(self) -> int:
        return self.pod_req.shape[0]

    @property
    def R(self) -> int:
        return self.node_alloc.shape[1]


def _resource_axis(snap: Snapshot) -> List[str]:
    res = list(_BASE_RESOURCES)
    seen = set(res)
    for obj in [*snap.nodes]:
        for k in obj.allocatable:
            if k not in seen:
                seen.add(k)
                res.append(k)
    for pod in [*snap.pending_pods, *snap.bound_pods]:
        for k in pod.requests:
            if k not in seen:
                seen.add(k)
                res.append(k)
    return res


def _scale_for(values: List[int]) -> int:
    """Exact-where-possible int32 rescale: gcd unit, widened if the max still
    overflows (widening rounds requests up / allocatable down — conservative)."""
    nz = [abs(x) for x in values if x]
    if not nz:
        return 1
    g = 0
    for x in nz:
        g = math.gcd(g, x)
    scale = max(1, g)
    while max(nz) // scale > _INT32_MAX:
        scale *= 2
    return scale


def pod_effective_requests(pod: t.Pod, resources: Sequence[str]) -> List[int]:
    """Pod-level request vector; every pod consumes 1 of the synthetic "pods"
    resource (reference: noderesources/fit.go — computePodResourceRequest +
    the NodeInfo pod-count check)."""
    return [pod.requests.get(r, 0) if r != t.PODS else max(1, pod.requests.get(r, 1)) for r in resources]


def activeq_order(pods: Sequence[t.Pod]) -> np.ndarray:
    """Indices sorting pods into activeQ pop order: priority desc, arrival asc
    (reference: queue sort plugin — PrioritySort.Less)."""
    return np.array(
        sorted(range(len(pods)), key=lambda i: (-pods[i].priority, i)), dtype=np.int64
    )


_IMG_MIN_MB = 23.0  # imagelocality/image_locality.go — minThreshold (23 MB)
_IMG_MAX_MB = 1000.0  # maxThreshold


def image_score_value(sum_mb: float) -> np.float32:
    """ImageLocality score from summed present-image megabytes (f32,
    mirrored by the oracle): 100 * (clip(sum) - min) / (max - min)."""
    s = np.float32(min(max(float(sum_mb), _IMG_MIN_MB), _IMG_MAX_MB))
    return np.float32(
        (s - np.float32(_IMG_MIN_MB))
        * np.float32(100.0)
        / np.float32(_IMG_MAX_MB - _IMG_MIN_MB)
    )


def _image_score_matrix(nodes, pending_sorted, N: int, P: int) -> np.ndarray:
    """f32[P, N] ImageLocality scores, or f32[P, 1] zeros when irrelevant.

    Image sizes quantize to whole MB so sums are integer-exact in f32 across
    numpy/XLA/C++ (reference computes in int64; imagelocality/image_locality.go
    — calculatePriority, sumImageScores without the spread factor — deviation
    documented in PARITY.md)."""
    img_ids: Dict[str, int] = {}
    for pod in pending_sorted:
        for im in pod.images:
            img_ids.setdefault(im, len(img_ids))
    if not img_ids or not any(nd.images for nd in nodes):
        return np.zeros((P, 1), dtype=np.float32)
    I = len(img_ids)
    node_mb = np.zeros((N, I), dtype=np.float32)
    for i, nd in enumerate(nodes):
        for im, size in nd.images.items():
            j = img_ids.get(im)
            if j is not None:
                node_mb[i, j] = np.float32(size // (1024 * 1024))
    pod_has = np.zeros((P, I), dtype=np.float32)
    for k, pod in enumerate(pending_sorted):
        for im in pod.images:
            pod_has[k, img_ids[im]] = 1.0
    raw = pod_has @ node_mb.T  # integer-valued f32 MB sums
    s = np.clip(raw, _IMG_MIN_MB, _IMG_MAX_MB).astype(np.float32)
    return (
        (s - np.float32(_IMG_MIN_MB))
        * np.float32(100.0)
        / np.float32(_IMG_MAX_MB - _IMG_MIN_MB)
    ).astype(np.float32)


def encode_snapshot(
    snap: Snapshot, *, bucket: bool = True, hard_pod_affinity_weight: float = 1.0
) -> Tuple[ClusterArrays, EncodingMeta]:
    from .volumes import resolve_snapshot

    snap = resolve_snapshot(snap)
    nodes, pending = snap.nodes, snap.pending_pods
    n, p = len(nodes), len(pending)
    N = _bucket(n) if bucket else max(1, n)
    P = _bucket(p) if bucket else max(1, p)

    resources = _resource_axis(snap)
    R = len(resources)

    # --- label vocab over node labels (selectors lower against this) ---
    # Only label KEYS referenced by some pod's nodeSelector / node-affinity
    # expression enter the literal vocab: unreferenced labels (notably the
    # per-node kubernetes.io/hostname) cannot influence any selector, and
    # would otherwise blow the L axis up to O(N).  Topology keys are interned
    # separately as domains (api/pairwise.py).
    referenced_keys = set()
    for pod in pending:
        for k, _ in pod.node_selector:
            referenced_keys.add(k)
        if pod.affinity:
            for term in pod.affinity.required_node_terms:
                for e in term.match_expressions:
                    referenced_keys.add(e.key)
            for pt in pod.affinity.preferred_node_terms:
                for e in pt.preference.match_expressions:
                    referenced_keys.add(e.key)
    lab = v.LabelVocab()
    node_lits: List[List[int]] = [
        lab.add_labels({k: val for k, val in nd.labels.items() if k in referenced_keys})
        for nd in nodes
    ]

    # --- taint vocab ---
    # spec.unschedulable is modeled as the synthetic taint the reference's node
    # controller applies (node.kubernetes.io/unschedulable:NoSchedule), which makes
    # the NodeUnschedulable plugin's toleration-aware check fall out of the taint
    # kernel (reference: nodeunschedulable/node_unschedulable.go — Filter).
    def _node_taints(nd: t.Node) -> List[t.Taint]:
        ts = list(nd.taints)
        if nd.unschedulable:
            ts.append(t.Taint(key="node.kubernetes.io/unschedulable", effect=t.NO_SCHEDULE))
        return ts

    taints = v.Interner()
    for nd in nodes:
        for tn in _node_taints(nd):
            taints.intern((tn.key, tn.value, tn.effect))
    T = max(1, len(taints))

    # --- raw quantities, then per-resource rescale to int32 ---
    alloc_raw = np.zeros((n, R), dtype=np.int64)
    for i, nd in enumerate(nodes):
        for j, r in enumerate(resources):
            if r == t.PODS:
                alloc_raw[i, j] = nd.allocatable.get(r, _DEFAULT_POD_LIMIT)
            else:
                alloc_raw[i, j] = nd.allocatable.get(r, 0)
    perm = activeq_order(pending)
    req_raw = np.zeros((p, R), dtype=np.int64)
    for out_i, src_i in enumerate(perm):
        req_raw[out_i] = pod_effective_requests(pending[src_i], resources)
    used_raw = np.zeros((n, R), dtype=np.int64)
    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    for bp in snap.bound_pods:
        i = node_index.get(bp.node_name)
        if i is not None:
            used_raw[i] += np.array(pod_effective_requests(bp, resources), dtype=np.int64)

    scale = np.ones(R, dtype=np.int64)
    for j in range(R):
        vals = [int(x) for x in alloc_raw[:, j]] + [int(x) for x in req_raw[:, j]] + [
            int(x) for x in used_raw[:, j]
        ]
        scale[j] = _scale_for(vals)
    # ceil for demand, floor for supply when the unit is inexact (conservative)
    req_s = -(-req_raw // scale)
    used_s = -(-used_raw // scale)
    alloc_s = alloc_raw // scale

    node_alloc = np.zeros((N, R), dtype=np.int32)
    node_used = np.zeros((N, R), dtype=np.int32)
    node_alloc[:n] = alloc_s
    node_used[:n] = used_s

    node_valid = np.zeros(N, dtype=bool)
    node_valid[:n] = True
    node_unsched = np.zeros(N, dtype=bool)
    node_unsched[:n] = [nd.unschedulable for nd in nodes]

    L = max(1, len(lab))
    node_labels = np.zeros((N, L), dtype=np.float32)
    for i, lits in enumerate(node_lits):
        node_labels[i, lits] = 1.0

    node_taint_ns = np.zeros((N, T), dtype=bool)
    node_taint_pref = np.zeros((N, T), dtype=bool)
    for i, nd in enumerate(nodes):
        for tn in _node_taints(nd):
            tid = taints.get((tn.key, tn.value, tn.effect))
            if tn.effect == t.PREFER_NO_SCHEDULE:
                node_taint_pref[i, tid] = True
            else:
                node_taint_ns[i, tid] = True

    # --- pods (in activeQ order) ---
    # SchedulingGates: gated pods never enter the schedulable set (reference:
    # schedulinggates/scheduling_gates.go — PreEnqueue holds them out of activeQ);
    # they come back with verdict -1 (still pending).
    pod_valid = np.zeros(P, dtype=bool)
    for out_i, src_i in enumerate(perm):
        pod_valid[out_i] = not pending[src_i].scheduling_gates
    pod_req = np.zeros((P, R), dtype=np.int32)
    pod_req[:p] = req_s
    pod_prio = np.zeros(P, dtype=np.int32)
    pod_tol_ns = np.ones((P, T), dtype=bool)  # default: padding tolerates all
    pod_tol_pref = np.ones((P, T), dtype=bool)
    pod_nodename = np.full(P, -1, dtype=np.int32)

    table = v.TermTable()
    pod_term_lists: List[List[int]] = []
    pref_lists: List[List[Tuple[int, float]]] = []
    for out_i, src_i in enumerate(perm):
        pod = pending[src_i]
        pod_prio[out_i] = pod.priority
        for tid, (tk, tv, te) in enumerate(taints.items):
            taint = t.Taint(tk, tv, te)
            tol = any(tol.tolerates(taint) for tol in pod.tolerations)
            if te == t.PREFER_NO_SCHEDULE:
                pod_tol_pref[out_i, tid] = tol
            else:
                pod_tol_ns[out_i, tid] = tol
        if pod.node_name:
            pod_nodename[out_i] = node_index.get(pod.node_name, -2)
        terms = v.pod_required_node_terms(pod, lab)
        pod_term_lists.append([] if terms is None else [table.intern(tm) for tm in terms])
        # preferred node affinity: weight per matching term (empty term matches
        # nothing, mirroring the required path)
        prefs: List[Tuple[int, float]] = []
        if pod.affinity:
            for pt in pod.affinity.preferred_node_terms:
                if pt.preference.match_expressions:
                    prefs.append(
                        (table.intern(v.lower_node_term(pt.preference.match_expressions, lab)), float(pt.weight))
                    )
        pref_lists.append(prefs)

    TT = max(1, max((len(x) for x in pod_term_lists), default=1))
    pod_terms = np.full((P, TT), -1, dtype=np.int32)
    pod_has_sel = np.zeros(P, dtype=bool)
    for i, ids in enumerate(pod_term_lists):
        if ids:
            pod_has_sel[i] = True
            pod_terms[i, : len(ids)] = ids

    PW = max(1, max((len(x) for x in pref_lists), default=1))
    pod_pref_terms = np.full((P, PW), -1, dtype=np.int32)
    pod_pref_weights = np.zeros((P, PW), dtype=np.float32)
    for i, prefs in enumerate(pref_lists):
        for a, (tid, w) in enumerate(prefs):
            pod_pref_terms[i, a] = tid
            pod_pref_weights[i, a] = w

    sel_mask, sel_kind = table.encode(L)

    # gang groups: pods referencing a PodGroup name share an index; minMember
    # defaults to the group's pod count when no PodGroup object is given
    group_ids = v.Interner()
    pod_group = np.full(P, -1, dtype=np.int32)
    for out_i, src_i in enumerate(perm):
        g = pending[src_i].pod_group
        if g:
            pod_group[out_i] = group_ids.intern(g)
    G = max(1, len(group_ids))
    group_min = np.ones(G, dtype=np.int32)
    for gi, gname in enumerate(group_ids.items):
        pg = snap.pod_groups.get(gname)
        group_min[gi] = pg.min_member if pg else int((pod_group == gi).sum())

    from .pairwise import build_pairwise

    sorted_pending = [pending[i] for i in perm]
    _pair_voc, pair = build_pairwise(
        nodes, sorted_pending, snap.bound_pods, node_index, N, P,
        hard_pod_affinity_weight=hard_pod_affinity_weight,
    )

    arrays = ClusterArrays(
        node_valid=node_valid,
        node_alloc=node_alloc,
        node_used=node_used,
        node_unsched=node_unsched,
        node_labels=node_labels,
        node_taint_ns=node_taint_ns,
        node_taint_pref=node_taint_pref,
        pod_valid=pod_valid,
        pod_req=pod_req,
        pod_prio=pod_prio,
        pod_tol_ns=pod_tol_ns,
        pod_tol_pref=pod_tol_pref,
        pod_nodename=pod_nodename,
        pod_terms=pod_terms,
        pod_has_sel=pod_has_sel,
        sel_mask=sel_mask,
        sel_kind=sel_kind,
        pod_pref_terms=pod_pref_terms,
        pod_pref_weights=pod_pref_weights,
        pod_group=pod_group,
        group_min=group_min,
        image_score=_image_score_matrix(nodes, sorted_pending, N, P),
        **pair,
    )
    meta = EncodingMeta(
        node_names=[nd.name for nd in nodes],
        pod_names=[pending[i].name for i in perm],
        pod_perm=perm,
        resources=resources,
        resource_scale=scale,
        label_vocab=lab,
        taint_vocab=taints,
        pairwise_vocab=_pair_voc,
        n_nodes=n,
        n_pods=p,
    )
    return arrays, meta
