"""Cluster object model — the fields the scheduler reads.

This is the TPU framework's analog of the reference's API types
(staging/src/k8s.io/api/core/v1/types.go — type Pod, type Node) restricted to the
scheduling-relevant surface: resource requests/allocatable, labels, taints and
tolerations, node selectors and (anti-)affinity, topology-spread constraints,
priority, host ports, and scheduling gates.  Everything else (status machinery,
volumes-as-objects, probes, ...) belongs to components SURVEY.md §7 scopes out.

Quantities are plain integers in canonical units chosen by the caller (the
convention used throughout tests and benchmarks: cpu in millicores, memory in
bytes, pods/extended resources in counts).  The snapshot encoder rescales each
resource axis independently so values fit int32 exactly (api/snapshot.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Canonical well-known resource names (reference: pkg/api/v1/resource,
# noderesources/fit.go default resources).  Extended resources are any other key.
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
DEFAULT_RESOURCES: Tuple[str, ...] = (CPU, MEMORY)

ResourceList = Dict[str, int]

# Taint effects (reference: core/v1/types.go — TaintEffect).
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Selector operators (reference: core/v1/types.go — NodeSelectorOperator,
# metav1 LabelSelectorOperator).
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"

# Topology-spread unsatisfiable policies (core/v1/types.go — UnsatisfiableConstraintAction).
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

# Well-known topology label keys (component-helpers; used for default spread constraints).
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"


@dataclass(frozen=True)
class Taint:
    """reference: core/v1/types.go — type Taint."""

    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    """reference: core/v1/types.go — type Toleration.

    operator "Equal" matches key+value; "Exists" matches any value of key.
    Empty key with operator Exists tolerates everything.  Empty effect matches
    all effects.  (tolerationSeconds only matters for NoExecute eviction, which
    is the node-lifecycle controller's job, not filtering's; carried for parity.)
    """

    key: str = ""
    operator: str = "Equal"
    value: str = ""
    effect: str = ""
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        # reference: component-helpers scheduling/corev1 — ToleratesTaint
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == OP_EXISTS:
            return True
        # Equal (default); empty key+Exists handled above via `self.key and ...`
        return self.value == taint.value


@dataclass(frozen=True)
class NodeSelectorRequirement:
    """reference: core/v1/types.go — type NodeSelectorRequirement."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class NodeSelectorTerm:
    """Conjunction of requirements; terms within a selector are ORed.

    reference: core/v1/types.go — type NodeSelectorTerm (matchFields folded into
    matchExpressions on the single supported field metadata.name).
    """

    match_expressions: Tuple[NodeSelectorRequirement, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass(frozen=True)
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """reference: apimachinery metav1 — type LabelSelector.

    match_labels is sugar for In-with-one-value requirements.  An empty selector
    matches everything; None (no selector) matches nothing.
    """

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[LabelSelectorRequirement, ...] = ()

    @staticmethod
    def of(**labels: str) -> "LabelSelector":
        return LabelSelector(match_labels=tuple(sorted(labels.items())))

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            has = req.key in labels
            val = labels.get(req.key)
            if req.operator == OP_IN:
                if not has or val not in req.values:
                    return False
            elif req.operator == OP_NOT_IN:
                if has and val in req.values:
                    return False
            elif req.operator == OP_EXISTS:
                if not has:
                    return False
            elif req.operator == OP_DOES_NOT_EXIST:
                if has:
                    return False
            else:
                raise ValueError(f"bad label selector operator {req.operator}")
        return True


@dataclass(frozen=True)
class PodAffinityTerm:
    """reference: core/v1/types.go — type PodAffinityTerm."""

    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: Tuple[str, ...] = ()  # empty => pod's own namespace


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm = None  # type: ignore[assignment]


@dataclass(frozen=True)
class Affinity:
    """reference: core/v1/types.go — type Affinity (node + pod + podAntiAffinity)."""

    # nodeAffinity
    required_node_terms: Tuple[NodeSelectorTerm, ...] = ()  # ORed; empty => no constraint
    preferred_node_terms: Tuple[PreferredSchedulingTerm, ...] = ()
    # podAffinity / podAntiAffinity (requiredDuringSchedulingIgnoredDuringExecution)
    required_pod_affinity: Tuple[PodAffinityTerm, ...] = ()
    required_pod_anti_affinity: Tuple[PodAffinityTerm, ...] = ()
    preferred_pod_affinity: Tuple[WeightedPodAffinityTerm, ...] = ()
    preferred_pod_anti_affinity: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """reference: core/v1/types.go — type TopologySpreadConstraint."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None


@dataclass(frozen=True)
class PersistentVolume:
    """Static-provisioned volume (core/v1 — type PersistentVolume), reduced to
    the scheduling-relevant surface: capacity, class, and topology (the node
    affinity the volume carries, typically a zone restriction)."""

    name: str
    capacity: int = 0  # bytes
    storage_class: str = ""
    # zone restriction: nodes must carry one of these (key, value) labels;
    # empty = accessible from everywhere
    allowed_topology: Tuple[Tuple[str, str], ...] = ()
    claim_ref: str = ""  # "namespace/name" of the bound PVC ("" = available)


@dataclass(frozen=True)
class PersistentVolumeClaim:
    """core/v1 — type PersistentVolumeClaim (scheduling surface)."""

    name: str
    namespace: str = "default"
    request: int = 0  # bytes
    storage_class: str = ""
    volume_name: str = ""  # pre-bound PV ("" = unbound)
    # WaitForFirstConsumer claims don't constrain scheduling (delayed binding)
    wait_for_first_consumer: bool = False
    # accessModes contains ReadWriteOncePod: at most ONE pod cluster-wide may
    # use the claim (volumerestrictions/volume_restrictions.go — the only
    # non-deprecated restriction the reference's plugin enforces)
    read_write_once_pod: bool = False

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class ResourceClaimRef:
    """DRA-lite (resource.k8s.io ResourceClaim reduced to counted device
    classes — the dynamicresources plugin's schedulable core): a claim for
    `count` devices of `device_class`, modeled as extended resources."""

    device_class: str
    count: int = 1


@dataclass
class Node:
    """Scheduling view of a node.

    reference: core/v1/types.go — type Node + the scheduler's aggregation of it
    (pkg/scheduler/framework/types.go — type NodeInfo).
    """

    name: str
    allocatable: ResourceList = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: Tuple[Taint, ...] = ()
    unschedulable: bool = False  # spec.unschedulable
    # spec.podCIDR — assigned by the NodeIPAM controller; the kubelet carves
    # pod IPs from it ("" = not yet assigned, kubelet falls back to a
    # process-local registry)
    pod_cidr: str = ""
    # image name -> size bytes present on the node (NodeStatus.Images;
    # ImageLocality's input)
    images: Dict[str, int] = field(default_factory=dict)
    # CSI attachable-volume limit (NodeVolumeLimits/csi.go); 0 = unlimited
    volume_attach_limit: int = 0
    # NodeStatus.VolumesAttached — PV names the attach/detach controller has
    # attached here (controllers.py — AttachDetachController)
    volumes_attached: Tuple[str, ...] = ()

    def __post_init__(self) -> None:
        self.labels.setdefault(LABEL_HOSTNAME, self.name)
        if type(self.taints) is not tuple:  # boundary normalization
            self.taints = tuple(self.taints)


@dataclass(frozen=True)
class OwnerReference:
    """metav1 — type OwnerReference (the GC graph edge + controller adoption)."""

    kind: str  # ReplicaSet | Deployment | Job | ...
    name: str
    uid: str
    controller: bool = True


# Pod phases (core/v1/types.go — type PodPhase); "" on a Pod means the phase
# machinery is not in play (bare scheduling harness objects)
PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"


@dataclass(frozen=True)
class Probe:
    """core/v1/types.go — type Probe (timing/threshold shape), reduced to
    what drives the hollow kubelet's prober: the reference's handler
    (httpGet/exec/tcpSocket) is replaced by a clock contract —
    `fail_after_seconds` > 0 means the probe starts FAILING once the
    container has been running that long (0 = always succeeds).  The
    kubemark trade, same as FakeCRI's run/crash knobs."""

    period_seconds: float = 10.0
    failure_threshold: int = 3
    # readiness only: a liveness probe with success_threshold != 1 is
    # rejected by Pod.__post_init__ (reference API validation)
    success_threshold: int = 1
    initial_delay_seconds: float = 0.0
    fail_after_seconds: float = 0.0  # hollow outcome knob


@dataclass
class Pod:
    """Scheduling view of a pod (pending or running).

    reference: core/v1/types.go — type Pod / PodSpec; requests aggregated the way
    pkg/scheduler/framework/plugins/noderesources — computePodResourceRequest does
    (max(sum(containers), initContainers) + overhead), which callers perform before
    constructing this object: `requests` here is the pod-level effective request.
    """

    name: str
    namespace: str = "default"
    requests: ResourceList = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    node_name: str = ""  # spec.nodeName: "" = pending; set = bound/running
    # spec.schedulerName: selects the scheduling profile ("" = the default
    # profile).  Pods naming a profile this scheduler does not serve are
    # ignored entirely — another scheduler's responsibility
    # (schedule_one.go — frameworkForPod)
    scheduler_name: str = ""
    priority_class_name: str = ""  # resolved to `priority` by Priority admission
    pod_ip: str = ""  # status.podIP, assigned by the kubelet when Running
    # status.nominatedNodeName: set by preemption; the node this pod's victims
    # were evicted from, reserved against lower-priority competitors
    nominated_node_name: str = ""
    priority: int = 0
    tolerations: Tuple[Toleration, ...] = ()
    node_selector: Tuple[Tuple[str, str], ...] = ()  # spec.nodeSelector (AND of k=v)
    affinity: Optional[Affinity] = None
    topology_spread: Tuple[TopologySpreadConstraint, ...] = ()
    host_ports: Tuple[Tuple[str, int], ...] = ()  # (protocol, port)
    scheduling_gates: Tuple[str, ...] = ()
    pod_group: str = ""  # gang-scheduling group name ("" = none)
    images: Tuple[str, ...] = ()  # container images (ImageLocality's input)
    pvcs: Tuple[str, ...] = ()  # claimed PVC names (in the pod's namespace)
    resource_claims: Tuple[ResourceClaimRef, ...] = ()  # DRA-lite
    owner_references: Tuple[OwnerReference, ...] = ()  # GC graph + adoption
    # status.phase ("": phase machinery not in play — bound implies running)
    phase: str = ""
    # clock time the pod reached Succeeded/Failed (-1 = not finished or
    # untimed); stamped by the kubelet, consumed by PodGC's oldest-first sweep
    finished_at: float = -1.0
    # lifecycle knob for the hollow kubelet: pods whose workload completes
    # (Job pods) run for run_seconds then succeed; 0 = run forever
    run_seconds: float = 0.0
    # spec.restartPolicy (Always | OnFailure | Never) — what the kubelet's
    # pod worker does when the (hollow) container dies unexpectedly
    restart_policy: str = "Always"
    # fault-injection knob (hollow runtime): the container crashes this many
    # seconds after each (re)start; 0 = never crashes
    crash_after_seconds: float = 0.0
    # status.containerStatuses[0].restartCount, stamped by the kubelet
    restart_count: int = 0
    # spec.containers[0].{liveness,readiness}Probe — run by the kubelet's
    # prober (pkg/kubelet/prober); liveness failure restarts the container,
    # readiness gates the pod's Ready condition (and so EndpointSlices)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    # status.conditions[Ready] — True when no readiness probe is configured
    # (the reference defaults readiness true absent a probe).  A pending pod
    # WITH a readiness probe is forced False in __post_init__ (initial
    # readiness is Failure) and stays False until the kubelet's prober has
    # seen success_threshold consecutive passes
    ready: bool = True
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"
        # reference API validation (core/validation — validateLivenessProbe):
        # a liveness probe's successThreshold must be 1; anything else is
        # rejected at admission, so reject it at construction here
        if (
            self.liveness_probe is not None
            and self.liveness_probe.success_threshold != 1
        ):
            raise ValueError(
                "liveness probe success_threshold must be 1 "
                f"(got {self.liveness_probe.success_threshold})"
            )
        # initial readiness is Failure under a readiness probe (the reference
        # holds the Ready condition false from creation until the probe has
        # passed success_threshold times) — without this a probed pod counts
        # Ready between bind and its first kubelet sync.  Only stamped on
        # still-pending pods: bound/running fixtures keep what they pass.
        if (
            self.readiness_probe is not None
            and not self.node_name
            and self.phase in ("", PHASE_PENDING)
        ):
            self.ready = False
        # Boundary normalization (the analog of apimachinery defaulting):
        # callers naturally pass lists / a dict nodeSelector; the encoder's
        # spec interner hashes these fields, so coerce them to the declared
        # tuple forms here rather than failing deep inside encode_snapshot.
        for f in (
            "tolerations", "topology_spread",
            "scheduling_gates", "images", "pvcs", "resource_claims",
            "owner_references",
        ):
            v = getattr(self, f)
            if type(v) is not tuple:
                setattr(self, f, tuple(v))
        # pair-valued fields coerce their inner pairs too (a list of
        # ["TCP", 80] pairs must hash); a dict nodeSelector sorts for a
        # canonical key
        if isinstance(self.node_selector, dict):
            self.node_selector = tuple(sorted(self.node_selector.items()))
        elif type(self.node_selector) is not tuple or any(
            type(kv) is not tuple for kv in self.node_selector
        ):
            self.node_selector = tuple(
                kv if type(kv) is tuple else tuple(kv)
                for kv in self.node_selector
            )
        if type(self.host_ports) is not tuple or any(
            type(pp) is not tuple for pp in self.host_ports
        ):
            self.host_ports = tuple(
                pp if type(pp) is tuple else tuple(pp)
                for pp in self.host_ports
            )


def pod_clone(pod: "Pod", **overrides) -> "Pod":
    """Shallow Pod clone: __new__ + __dict__ copy (~4x cheaper than
    copy.copy's reduce machinery at wave/bind rates), with field objects
    SHARED with the source — the invariant the encoder's identity-level
    interning and bind-absorb `is`-checks depend on.  THE one clone idiom:
    every hot path (store binding, sidecar wave decode, session bind
    copies) must route here so a future Pod change (slots, cached
    properties) has one place to fix."""
    q = Pod.__new__(Pod)
    d = pod.__dict__.copy()
    d.update(overrides)
    q.__dict__ = d
    return q


@dataclass(frozen=True)
class PodGroup:
    """Gang-scheduling group (analog of out-of-tree coscheduling PodGroup CRD;
    BASELINE config 5)."""

    name: str
    min_member: int


@dataclass
class ReplicaSet:
    """apps/v1 — type ReplicaSet (workload-controller surface): desired
    replicas + selector + pod template.  `template` is a prototype Pod whose
    name becomes the stamped pods' name prefix."""

    name: str
    namespace: str = "default"
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional["Pod"] = None
    owner_references: Tuple[OwnerReference, ...] = ()
    uid: str = ""
    # status
    ready_replicas: int = 0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"rs/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Deployment:
    """apps/v1 — type Deployment: declarative rollout over ReplicaSets.
    Strategy reduced to RollingUpdate with maxSurge/maxUnavailable counts."""

    name: str
    namespace: str = "default"
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional["Pod"] = None
    max_surge: int = 1
    max_unavailable: int = 0
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"deploy/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Job:
    """batch/v1 — type Job: run pods to completion (completions/parallelism).
    ttl_seconds_after_finished drives the TTL-after-finished controller."""

    name: str
    namespace: str = "default"
    completions: int = 1
    parallelism: int = 1
    template: Optional["Pod"] = None
    ttl_seconds_after_finished: Optional[int] = None
    owner_references: Tuple[OwnerReference, ...] = ()  # CronJob -> Job edge
    uid: str = ""
    # status
    succeeded: int = 0
    active: int = 0
    completion_time: float = -1.0  # clock time the job finished (-1 = not yet)

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"job/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def complete(self) -> bool:
        return self.succeeded >= self.completions


@dataclass
class PodDisruptionBudget:
    """policy/v1 — type PodDisruptionBudget, reduced to the scheduling surface
    the preemption evaluator reads (reference: defaultpreemption reads PDBs via
    a PDB lister and counts violations in SelectVictimsOnNode).

    Exactly one of min_available / max_unavailable is meaningful; both are
    absolute counts (the reference also accepts percentages, resolved against
    the expected count by the disruption controller — callers here pre-resolve).
    `disruptions_allowed` is status, maintained by the DisruptionController.
    """

    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None
    # status (pkg/controller/disruption — updatePdbStatus)
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def matches(self, pod: "Pod") -> bool:
        return (
            pod.namespace == self.namespace
            and self.selector is not None
            and self.selector.matches(pod.labels)
        )
