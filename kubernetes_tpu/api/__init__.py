from .types import (  # noqa: F401
    Affinity,
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinityTerm,
    PodGroup,
    PreferredSchedulingTerm,
    ResourceList,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from .snapshot import ClusterArrays, Snapshot, encode_snapshot  # noqa: F401
