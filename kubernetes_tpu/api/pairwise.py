"""Encoding for the pairwise plugins: PodTopologySpread, InterPodAffinity,
NodePorts — the O(pods x pods) / O(pods x nodes) hot spots of the reference
(SURVEY.md §2.2 ①: interpodaffinity/filtering.go, podtopologyspread/filtering.go).

TPU-first reformulation: every (pod-label-selector, namespace-set, topologyKey)
triple appearing in any spread constraint or (anti-)affinity term is interned as
a *term* t.  The cluster-side state each plugin needs then collapses to

  counts[t, d]      # matching pods per topology domain d (domain = interned
                    # (key, value); column D = "node lacks the key")
  anti_counts[t, d] # pods OWNING anti-affinity term t, per their domain

maintained as scan-carried state in ops/assign.py: committing a pod scatter-adds
its term-match row M[:, p] (and its own anti terms) at the chosen node's domain
column.  Per-step feasibility/score checks are [N]-gathers of counts through the
static node->domain map — no per-pod string work ever reaches the device.

Selector-vs-pod matching itself (M_pend[T, P], and the counts0 initialisation
from bound pods) is one host-side 0/1 matmul over the pod-label literal vocab.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import types as t
from . import vocab as v

# spread modes
HARD = 1  # DoNotSchedule -> Filter
SOFT = 0  # ScheduleAnyway -> Score only


@dataclass(frozen=True)
class TermKey:
    """Interned identity of a pairwise term."""

    topology_key: str
    namespaces: Tuple[str, ...]
    selector: Optional[t.LabelSelector]  # None matches nothing


@dataclass
class PairwiseVocab:
    topo_keys: v.Interner  # topology key -> k
    domains: v.Interner  # (key, value) -> d  (D == len == "absent" sentinel)
    terms: v.Interner  # TermKey -> t
    ports: v.Interner  # (protocol, port) -> id


def _term_of_affinity(term: t.PodAffinityTerm, pod_ns: str) -> TermKey:
    ns = tuple(sorted(term.namespaces)) if term.namespaces else (pod_ns,)
    return TermKey(term.topology_key, ns, term.label_selector)


def _term_of_spread(c: t.TopologySpreadConstraint, pod_ns: str) -> TermKey:
    # spread counts pods in the pod's own namespace (reference:
    # podtopologyspread/common.go — the constraint selector is namespace-scoped)
    return TermKey(c.topology_key, (pod_ns,), c.label_selector)


def _matches(term: TermKey, pod: t.Pod) -> bool:
    if term.selector is None:
        return False
    return pod.namespace in term.namespaces and term.selector.matches(pod.labels)


_NS_KEY = "\x00ns"  # pseudo label key carrying the pod's namespace


def _match_matrix(terms: List[TermKey], pods: Sequence[t.Pod]) -> np.ndarray:
    """f32[T, P] 0/1 selector+namespace matches, vectorized.

    Reuses the AnyOf/NoneOf lowering (api/vocab.py) over a POD-label literal
    vocab — the namespace test becomes one more AnyOf over pseudo-literals —
    so the whole match is a handful of numpy matmuls instead of T x P Python
    selector evaluations.  Semantics identical to _matches (property-tested).
    """
    T, P = len(terms), len(pods)
    if T == 0 or P == 0:
        return np.zeros((max(1, T), max(1, P)), dtype=np.float32)
    voc = v.LabelVocab()
    pod_lits = [
        voc.add_labels({**pod.labels, _NS_KEY: pod.namespace}) for pod in pods
    ]
    L = max(1, len(voc))
    labels = np.zeros((P, L), dtype=np.float32)
    for i, lits in enumerate(pod_lits):
        labels[i, lits] = 1.0

    table = v.TermTable()
    ids = []
    for term in terms:
        if term.selector is None:
            ids.append(table.intern(v.FALSE_TERM))
            continue
        reqs = v.label_selector_to_requirements(term.selector)
        lowered = v.lower_node_term(reqs, voc)
        if lowered is not v.FALSE_TERM:
            ns_lits = frozenset(
                l
                for ns in term.namespaces
                if (l := voc.lit(_NS_KEY, ns)) is not None
            )
            if not ns_lits:
                lowered = v.FALSE_TERM
            else:
                lowered = tuple(sorted([*lowered, (v.KIND_ANY, ns_lits)],
                                       key=lambda e: (e[0], sorted(e[1]))))
        ids.append(table.intern(lowered))
    mask, kind = table.encode(L)  # [S, E, L], [S, E]
    counts = np.einsum("sel,pl->sep", mask, labels)
    ok = np.where(
        kind[:, :, None] == v.KIND_ANY,
        counts > 0,
        np.where(kind[:, :, None] == v.KIND_NONE, counts == 0, kind[:, :, None] == v.KIND_PAD),
    ).all(axis=1)  # [S, P]
    return ok[np.array(ids)].astype(np.float32)


