"""Encoding for the pairwise plugins: PodTopologySpread, InterPodAffinity,
NodePorts — the O(pods x pods) / O(pods x nodes) hot spots of the reference
(SURVEY.md §2.2 ①: interpodaffinity/filtering.go, podtopologyspread/filtering.go).

TPU-first reformulation: every (pod-label-selector, namespace-set, topologyKey)
triple appearing in any spread constraint or (anti-)affinity term is interned as
a *term* t.  The cluster-side state each plugin needs then collapses to

  counts[t, d]      # matching pods per topology domain d (domain = interned
                    # (key, value); column D = "node lacks the key")
  anti_counts[t, d] # pods OWNING anti-affinity term t, per their domain

maintained as scan-carried state in ops/assign.py: committing a pod scatter-adds
its term-match row M[:, p] (and its own anti terms) at the chosen node's domain
column.  Per-step feasibility/score checks are [N]-gathers of counts through the
static node->domain map — no per-pod string work ever reaches the device.

Selector-vs-pod matching itself (M_pend[T, P], and the counts0 initialisation
from bound pods) is one host-side 0/1 matmul over the pod-label literal vocab.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import types as t
from . import vocab as v

# spread modes
HARD = 1  # DoNotSchedule -> Filter
SOFT = 0  # ScheduleAnyway -> Score only


@dataclass(frozen=True)
class TermKey:
    """Interned identity of a pairwise term."""

    topology_key: str
    namespaces: Tuple[str, ...]
    selector: Optional[t.LabelSelector]  # None matches nothing


@dataclass
class PairwiseVocab:
    topo_keys: v.Interner  # topology key -> k
    domains: v.Interner  # (key, value) -> d  (D == len == "absent" sentinel)
    terms: v.Interner  # TermKey -> t
    ports: v.Interner  # (protocol, port) -> id


def _term_of_affinity(term: t.PodAffinityTerm, pod_ns: str) -> TermKey:
    ns = tuple(sorted(term.namespaces)) if term.namespaces else (pod_ns,)
    return TermKey(term.topology_key, ns, term.label_selector)


def _term_of_spread(c: t.TopologySpreadConstraint, pod_ns: str) -> TermKey:
    # spread counts pods in the pod's own namespace (reference:
    # podtopologyspread/common.go — the constraint selector is namespace-scoped)
    return TermKey(c.topology_key, (pod_ns,), c.label_selector)


def _matches(term: TermKey, pod: t.Pod) -> bool:
    if term.selector is None:
        return False
    return pod.namespace in term.namespaces and term.selector.matches(pod.labels)


_NS_KEY = "\x00ns"  # pseudo label key carrying the pod's namespace


def _match_matrix(terms: List[TermKey], pods: Sequence[t.Pod]) -> np.ndarray:
    """f32[T, P] 0/1 selector+namespace matches, vectorized.

    Reuses the AnyOf/NoneOf lowering (api/vocab.py) over a POD-label literal
    vocab — the namespace test becomes one more AnyOf over pseudo-literals —
    so the whole match is a handful of numpy matmuls instead of T x P Python
    selector evaluations.  Semantics identical to _matches (property-tested).
    """
    T, P = len(terms), len(pods)
    if T == 0 or P == 0:
        return np.zeros((max(1, T), max(1, P)), dtype=np.float32)
    voc = v.LabelVocab()
    pod_lits = [
        voc.add_labels({**pod.labels, _NS_KEY: pod.namespace}) for pod in pods
    ]
    L = max(1, len(voc))
    labels = np.zeros((P, L), dtype=np.float32)
    for i, lits in enumerate(pod_lits):
        labels[i, lits] = 1.0

    table = v.TermTable()
    ids = []
    for term in terms:
        if term.selector is None:
            ids.append(table.intern(v.FALSE_TERM))
            continue
        reqs = v.label_selector_to_requirements(term.selector)
        lowered = v.lower_node_term(reqs, voc)
        if lowered is not v.FALSE_TERM:
            ns_lits = frozenset(
                l
                for ns in term.namespaces
                if (l := voc.lit(_NS_KEY, ns)) is not None
            )
            if not ns_lits:
                lowered = v.FALSE_TERM
            else:
                lowered = tuple(sorted([*lowered, (v.KIND_ANY, ns_lits)],
                                       key=lambda e: (e[0], sorted(e[1]))))
        ids.append(table.intern(lowered))
    mask, kind = table.encode(L)  # [S, E, L], [S, E]
    counts = np.einsum("sel,pl->sep", mask, labels)
    ok = np.where(
        kind[:, :, None] == v.KIND_ANY,
        counts > 0,
        np.where(kind[:, :, None] == v.KIND_NONE, counts == 0, kind[:, :, None] == v.KIND_PAD),
    ).all(axis=1)  # [S, P]
    return ok[np.array(ids)].astype(np.float32)


def build_pairwise(
    nodes: Sequence[t.Node],
    pending: Sequence[t.Pod],  # unique specs in first-occurrence activeQ order
    bound: Sequence[t.Pod],
    node_index: Dict[str, int],
    N: int,
    P: int,
    hard_pod_affinity_weight: float = 1.0,
    pending_inv: Optional[np.ndarray] = None,
):
    """Returns (PairwiseVocab, dict of arrays) — see ClusterArrays for shapes.

    `pending` holds the UNIQUE pending-pod specs (snapshot.group_by_spec) and
    `pending_inv[i]` each sorted pod's spec index: per-spec term collection and
    the match matmul run over U specs, and rows scatter to the P pod axis.
    Omitting pending_inv treats `pending` as the literal per-pod list."""
    if pending_inv is None:
        pending_inv = np.arange(len(pending), dtype=np.int64)
    inv = pending_inv
    p = len(inv)
    voc = PairwiseVocab(v.Interner(), v.Interner(), v.Interner(), v.Interner())

    # ---- collect terms from every pending AND bound pod (bound pods' anti
    # terms constrain incoming pods symmetrically) ----
    pod_aff: List[List[int]] = []
    pod_anti: List[List[int]] = []
    pod_pref: List[List[Tuple[int, float]]] = []  # (term, signed weight)
    pod_spread: List[List[Tuple[int, int, int]]] = []  # (term, maxSkew, mode)
    for pod in pending:
        aff_ids, anti_ids, spread_ids = [], [], []
        pref_ids: List[Tuple[int, float]] = []
        if pod.affinity:
            for term in pod.affinity.required_pod_affinity:
                aff_ids.append(voc.terms.intern(_term_of_affinity(term, pod.namespace)))
            for term in pod.affinity.required_pod_anti_affinity:
                anti_ids.append(voc.terms.intern(_term_of_affinity(term, pod.namespace)))
            for wt in pod.affinity.preferred_pod_affinity:
                pref_ids.append(
                    (voc.terms.intern(_term_of_affinity(wt.term, pod.namespace)), float(wt.weight))
                )
            for wt in pod.affinity.preferred_pod_anti_affinity:
                pref_ids.append(
                    (voc.terms.intern(_term_of_affinity(wt.term, pod.namespace)), -float(wt.weight))
                )
        for c in pod.topology_spread:
            spread_ids.append(
                (
                    voc.terms.intern(_term_of_spread(c, pod.namespace)),
                    c.max_skew,
                    HARD if c.when_unsatisfiable == t.DO_NOT_SCHEDULE else SOFT,
                )
            )
        pod_aff.append(aff_ids)
        pod_anti.append(anti_ids)
        pod_pref.append(pref_ids)
        pod_spread.append(spread_ids)

    # bound pods intern by (labels, namespace, affinity): term collection and
    # the bound-side match matmul run once per unique spec
    b_ids: Dict[Tuple, int] = {}
    b_reps: List[t.Pod] = []
    b_inv: List[int] = []
    b_nodes: List[int] = []
    for q in bound:
        ni = node_index.get(q.node_name)
        if ni is None:
            continue
        key = (tuple(sorted(q.labels.items())), q.namespace, q.affinity)
        u = b_ids.get(key)
        if u is None:
            u = len(b_reps)
            b_ids[key] = u
            b_reps.append(q)
        b_inv.append(u)
        b_nodes.append(ni)
    bound_anti: List[List[int]] = []
    bound_pref: List[List[Tuple[int, float]]] = []
    for pod in b_reps:
        ids = []
        pref_ids = []
        if pod.affinity:
            for term in pod.affinity.required_pod_anti_affinity:
                ids.append(voc.terms.intern(_term_of_affinity(term, pod.namespace)))
            for wt in pod.affinity.preferred_pod_affinity:
                pref_ids.append(
                    (voc.terms.intern(_term_of_affinity(wt.term, pod.namespace)), float(wt.weight))
                )
            for wt in pod.affinity.preferred_pod_anti_affinity:
                pref_ids.append(
                    (voc.terms.intern(_term_of_affinity(wt.term, pod.namespace)), -float(wt.weight))
                )
            if hard_pod_affinity_weight:
                # existing pods' REQUIRED affinity terms score toward incoming
                # pods at hardPodAffinityWeight (scoring.go — processExistingPod)
                for term in pod.affinity.required_pod_affinity:
                    pref_ids.append(
                        (
                            voc.terms.intern(_term_of_affinity(term, pod.namespace)),
                            float(hard_pod_affinity_weight),
                        )
                    )
        bound_anti.append(ids)
        bound_pref.append(pref_ids)

    # ---- topology keys + domains over the node set ----
    for tk in [tm.topology_key for tm in voc.terms.items]:
        voc.topo_keys.intern(tk)
    K = max(1, len(voc.topo_keys))
    for nd in nodes:
        for tk in voc.topo_keys.items:
            if tk in nd.labels:
                voc.domains.intern((tk, nd.labels[tk]))
    D = len(voc.domains)  # sentinel column D = key absent

    node_dom = np.full((K, N), D, dtype=np.int32)
    for i, nd in enumerate(nodes):
        for k, tk in enumerate(voc.topo_keys.items):
            if tk in nd.labels:
                node_dom[k, i] = voc.domains.get((tk, nd.labels[tk]))

    T = max(1, len(voc.terms))
    term_key = np.zeros(T, dtype=np.int32)
    for ti, term in enumerate(voc.terms.items):
        term_key[ti] = voc.topo_keys.get(term.topology_key)

    # ---- host-side match matrices: vectorized AnyOf/NoneOf matmuls over
    # unique specs, gathered per pod ----
    terms_list = list(voc.terms.items)
    m_pend = np.zeros((T, P), dtype=np.float32)
    if p:
        m_uniq = _match_matrix(terms_list, pending)  # [T, U]
        m_pend[: m_uniq.shape[0], :p] = m_uniq[:, inv]
    bnodes = np.array(b_nodes, dtype=np.int64)
    binv = np.array(b_inv, dtype=np.int64)
    term_counts0 = np.zeros((T, D + 1), dtype=np.float32)
    if len(bnodes) and terms_list:
        m_bound_u = _match_matrix(terms_list, b_reps)  # [T, Ub]
        for ti in range(len(terms_list)):
            np.add.at(
                term_counts0[ti], node_dom[term_key[ti], bnodes], m_bound_u[ti, binv]
            )
    # group bound pods by unique spec once (argsort) so the anti/pref scatters
    # touch only specs that own terms
    anti_counts0 = np.zeros((T, D + 1), dtype=np.float32)
    pref_own0 = np.zeros((T, D + 1), dtype=np.float32)
    if len(bnodes):
        order = np.argsort(binv, kind="stable")
        starts = np.searchsorted(binv[order], np.arange(len(b_reps) + 1))
        for u in range(len(b_reps)):
            ids = bound_anti[u]
            prefs = bound_pref[u]
            if not ids and not prefs:
                continue
            rows = bnodes[order[starts[u] : starts[u + 1]]]
            for ti in ids:
                np.add.at(anti_counts0[ti], node_dom[term_key[ti], rows], 1.0)
            # weight-weighted counts of existing pods OWNING preferred terms,
            # per their domain (the symmetric half of preferred scoring)
            for ti, w in prefs:
                np.add.at(pref_own0[ti], node_dom[term_key[ti], rows], np.float32(w))

    # ---- per-pod term id arrays (padded; built per spec, gathered) ----
    A1 = max(1, max((len(x) for x in pod_aff), default=1))
    A2 = max(1, max((len(x) for x in pod_anti), default=1))
    B = max(1, max((len(x) for x in pod_pref), default=1))
    C = max(1, max((len(x) for x in pod_spread), default=1))
    Uq = max(1, len(pending))
    u_aff = np.full((Uq, A1), -1, dtype=np.int32)
    u_anti = np.full((Uq, A2), -1, dtype=np.int32)
    u_pref_t = np.full((Uq, B), -1, dtype=np.int32)
    u_pref_w = np.zeros((Uq, B), dtype=np.float32)
    u_spread_t = np.full((Uq, C), -1, dtype=np.int32)
    u_spread_skew = np.zeros((Uq, C), dtype=np.int32)
    u_spread_hard = np.zeros((Uq, C), dtype=bool)
    for ui in range(len(pending)):
        for a, ti in enumerate(pod_aff[ui]):
            u_aff[ui, a] = ti
        for a, ti in enumerate(pod_anti[ui]):
            u_anti[ui, a] = ti
        for a, (ti, w) in enumerate(pod_pref[ui]):
            u_pref_t[ui, a] = ti
            u_pref_w[ui, a] = np.float32(w)
        for c, (ti, skew, mode) in enumerate(pod_spread[ui]):
            u_spread_t[ui, c] = ti
            u_spread_skew[ui, c] = skew
            u_spread_hard[ui, c] = mode == HARD
    pod_aff_terms = np.full((P, A1), -1, dtype=np.int32)
    pod_anti_terms = np.full((P, A2), -1, dtype=np.int32)
    pod_pref_aff_terms = np.full((P, B), -1, dtype=np.int32)
    pod_pref_aff_w = np.zeros((P, B), dtype=np.float32)
    pod_spread_terms = np.full((P, C), -1, dtype=np.int32)
    pod_spread_maxskew = np.zeros((P, C), dtype=np.int32)
    pod_spread_hard = np.zeros((P, C), dtype=bool)
    if p:
        pod_aff_terms[:p] = u_aff[inv]
        pod_anti_terms[:p] = u_anti[inv]
        pod_pref_aff_terms[:p] = u_pref_t[inv]
        pod_pref_aff_w[:p] = u_pref_w[inv]
        pod_spread_terms[:p] = u_spread_t[inv]
        pod_spread_maxskew[:p] = u_spread_skew[inv]
        pod_spread_hard[:p] = u_spread_hard[inv]

    # ---- host ports ----
    for pod in pending:
        for proto, port in pod.host_ports:
            voc.ports.intern((proto, port))
    for pod in bound:
        for proto, port in pod.host_ports:
            voc.ports.intern((proto, port))
    PT = max(1, len(voc.ports))
    u_ports = np.zeros((Uq, PT), dtype=bool)
    for ui, pod in enumerate(pending):
        for proto, port in pod.host_ports:
            u_ports[ui, voc.ports.get((proto, port))] = True
    pod_ports = np.zeros((P, PT), dtype=bool)
    if p:
        pod_ports[:p] = u_ports[inv]
    node_ports0 = np.zeros((N, PT), dtype=bool)
    for pod in bound:
        ni = node_index.get(pod.node_name)
        if ni is None:
            continue
        for proto, port in pod.host_ports:
            node_ports0[ni, voc.ports.get((proto, port))] = True

    arrays = dict(
        node_dom=node_dom,
        term_key=term_key,
        m_pend=m_pend,
        term_counts0=term_counts0,
        anti_counts0=anti_counts0,
        pod_aff_terms=pod_aff_terms,
        pod_anti_terms=pod_anti_terms,
        pod_pref_aff_terms=pod_pref_aff_terms,
        pod_pref_aff_w=pod_pref_aff_w,
        pref_own0=pref_own0,
        pod_spread_terms=pod_spread_terms,
        pod_spread_maxskew=pod_spread_maxskew,
        pod_spread_hard=pod_spread_hard,
        pod_ports=pod_ports,
        node_ports0=node_ports0,
    )
    return voc, arrays
