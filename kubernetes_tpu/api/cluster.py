"""Control-plane object kinds beyond the scheduling surface.

The reference's API groups the harness-side components consume:
core/v1 Service/Endpoints/Namespace/ResourceQuota/LimitRange,
scheduling.k8s.io PriorityClass, discovery.k8s.io EndpointSlice,
apps/v1 StatefulSet/DaemonSet, batch/v1 CronJob, autoscaling/v2 HPA,
rbac.authorization.k8s.io Role/RoleBinding, flowcontrol.apiserver.k8s.io
FlowSchema/PriorityLevelConfiguration, storage.k8s.io StorageClass, and
resource.k8s.io ResourceSlice/DeviceClass (DRA structured parameters).

All reduced to the fields this framework's controllers/authorizers/allocators
actually read, same convention as api/types.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .types import LabelSelector, Pod, ResourceList

# ---------------------------------------------------------------- Services


@dataclass(frozen=True)
class ServicePort:
    """core/v1 — type ServicePort."""

    port: int
    target_port: int = 0  # 0 => same as port
    protocol: str = "TCP"
    name: str = ""

    @property
    def backend_port(self) -> int:
        return self.target_port or self.port


@dataclass
class Service:
    """core/v1 — type Service (ClusterIP surface).  spec.selector is a plain
    label map in the reference (not a LabelSelector)."""

    name: str
    namespace: str = "default"
    selector: Tuple[Tuple[str, str], ...] = ()
    ports: Tuple[ServicePort, ...] = ()
    cluster_ip: str = ""  # allocated by the apiserver facade ("" = to allocate)
    session_affinity: str = "None"  # None | ClientIP
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"svc/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def selects(self, pod: Pod) -> bool:
        if not self.selector or pod.namespace != self.namespace:
            return False
        return all(pod.labels.get(k) == v for k, v in self.selector)


@dataclass(frozen=True)
class Endpoint:
    """discovery/v1 — type Endpoint (one backend)."""

    address: str
    pod_uid: str = ""
    node_name: str = ""
    ready: bool = True


@dataclass
class EndpointSlice:
    """discovery/v1 — type EndpointSlice; owned by its Service, maintained by
    the EndpointSliceController."""

    name: str
    namespace: str = "default"
    service_name: str = ""  # kubernetes.io/service-name label
    endpoints: Tuple[Endpoint, ...] = ()
    ports: Tuple[ServicePort, ...] = ()
    owner_references: tuple = ()
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"eps/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ------------------------------------------------------------ Namespaces etc.


@dataclass
class Namespace:
    """core/v1 — type Namespace; phase drives the NamespaceLifecycle admission
    plugin and the namespace controller's cascading deletion."""

    name: str
    phase: str = "Active"  # Active | Terminating
    labels: Dict[str, str] = field(default_factory=dict)
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"ns/{self.name}"

    @property
    def key(self) -> str:
        return self.name


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 — type PriorityClass (the Priority admission
    plugin resolves pod.spec.priorityClassName through these)."""

    name: str
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"pc/{self.name}"

    @property
    def key(self) -> str:
        return self.name


@dataclass
class ResourceQuota:
    """core/v1 — type ResourceQuota: hard per-namespace caps on aggregate
    requests + object counts ("pods")."""

    name: str
    namespace: str = "default"
    hard: ResourceList = field(default_factory=dict)
    used: ResourceList = field(default_factory=dict)  # status
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"quota/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class LimitRange:
    """core/v1 — type LimitRange reduced to defaultRequest + max per pod
    (the LimitRanger admission plugin's surface)."""

    name: str
    namespace: str = "default"
    default_request: ResourceList = field(default_factory=dict)
    max_per_pod: ResourceList = field(default_factory=dict)
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"limits/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ------------------------------------------------------------------ Workloads


@dataclass
class StatefulSet:
    """apps/v1 — type StatefulSet: stable ordinal identities name-0..name-N-1,
    OrderedReady (default) or Parallel pod management."""

    name: str
    namespace: str = "default"
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[Pod] = None
    pod_management_policy: str = "OrderedReady"  # or "Parallel"
    uid: str = ""
    ready_replicas: int = 0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"sts/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class DaemonSet:
    """apps/v1 — type DaemonSet: one pod per eligible node, pinned via
    node-affinity to metadata.name (the reference schedules daemon pods
    through the default scheduler with a per-node nodeAffinity since 1.12 —
    daemon_controller.go NodeShouldRunDaemonPod)."""

    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    template: Optional[Pod] = None
    uid: str = ""
    desired_number_scheduled: int = 0
    number_ready: int = 0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"ds/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class CronJob:
    """batch/v1 — type CronJob with the schedule reduced to a period in
    seconds (cron-expression parsing is presentation, not semantics; the
    controller logic — missed-run catch-up, concurrencyPolicy — is the part
    worth reproducing from cronjob_controllerv2.go)."""

    name: str
    namespace: str = "default"
    period_seconds: float = 60.0
    job_template: Optional[Pod] = None
    completions: int = 1
    parallelism: int = 1
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    suspend: bool = False
    uid: str = ""
    last_schedule_time: float = -1.0  # status

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"cj/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v2 — type HorizontalPodAutoscaler: scale a Deployment
    between min/max replicas toward a target average metric value.  The
    controller applies the reference's ratio formula + tolerance
    (podautoscaler/replica_calculator.go)."""

    name: str
    namespace: str = "default"
    target_kind: str = "Deployment"
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    metric_name: str = "cpu"
    target_value: float = 0.5  # target average utilization/value per pod
    tolerance: float = 0.1
    uid: str = ""
    # status
    current_replicas: int = 0
    desired_replicas: int = 0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"hpa/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ------------------------------------------------------------- ServiceAccount


@dataclass
class ServiceAccount:
    """core/v1 — type ServiceAccount.  `token` is the minted bearer token
    (the legacy token Secret collapsed onto the object; the token controller
    fills it and registers it with the authenticator)."""

    name: str
    namespace: str = "default"
    token: str = ""  # "" = not yet minted
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"sa/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def username(self) -> str:
        """The authenticated identity (serviceaccount/util — MakeUsername)."""
        return f"system:serviceaccount:{self.namespace}:{self.name}"


# ------------------------------------------------------------------ Events


@dataclass
class ClusterEvent:
    """core/v1 — type Event (kind "Event"), reduced to the scheduling event
    surface with the reference's count-based aggregation: repeated identical
    events bump `count`/`last_seen` instead of creating new objects
    (client-go tools/record — EventAggregator)."""

    name: str
    namespace: str = "default"
    reason: str = ""  # Scheduled | FailedScheduling | Preempted | ...
    involved_object: str = ""  # "Pod/<ns>/<name>"
    node: str = ""
    message: str = ""
    count: int = 1
    first_seen: float = 0.0
    last_seen: float = 0.0
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"ev/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ------------------------------------------------------------------ RBAC


@dataclass(frozen=True)
class PolicyRule:
    """rbac/v1 — type PolicyRule; "*" wildcards supported on verbs and
    resources (plugin/pkg/auth/authorizer/rbac — RuleAllows)."""

    verbs: Tuple[str, ...] = ()
    resources: Tuple[str, ...] = ()
    resource_names: Tuple[str, ...] = ()


@dataclass
class Role:
    """rbac/v1 — Role (namespaced) / ClusterRole (namespace="")."""

    name: str
    namespace: str = ""  # "" = ClusterRole
    rules: Tuple[PolicyRule, ...] = ()
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"role/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        # cluster-scoped (ClusterRole) objects key by bare name
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


@dataclass(frozen=True)
class Subject:
    """rbac/v1 — type Subject."""

    kind: str  # User | Group | ServiceAccount
    name: str


@dataclass
class RoleBinding:
    """rbac/v1 — RoleBinding (namespaced) / ClusterRoleBinding (namespace="")."""

    name: str
    namespace: str = ""  # "" = ClusterRoleBinding
    role_name: str = ""
    role_namespace: str = ""  # "" = refers to a ClusterRole
    subjects: Tuple[Subject, ...] = ()
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"rb/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        # cluster-scoped (ClusterRoleBinding) objects key by bare name
        return f"{self.namespace}/{self.name}" if self.namespace else self.name


@dataclass(frozen=True)
class UserInfo:
    """authentication/user — type DefaultInfo."""

    name: str
    groups: Tuple[str, ...] = ()


# ----------------------------------------------------- API Priority & Fairness


@dataclass
class FlowSchema:
    """flowcontrol/v1 — type FlowSchema: classify a request to a priority
    level, with a flow distinguisher (per-user here, the common case)."""

    name: str
    priority_level: str = ""
    matching_precedence: int = 1000  # lower = matched first
    # match: any of these subjects ("*" = all), any of these resources
    subjects: Tuple[str, ...] = ("*",)
    resources: Tuple[str, ...] = ("*",)
    verbs: Tuple[str, ...] = ("*",)
    distinguisher: str = "ByUser"  # ByUser | ByNamespace | "" (single flow)
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"fs/{self.name}"

    @property
    def key(self) -> str:
        return self.name


@dataclass
class PriorityLevelConfiguration:
    """flowcontrol/v1 — type PriorityLevelConfiguration (Limited type):
    concurrency shares + fair queuing parameters."""

    name: str
    concurrency_shares: int = 30
    queues: int = 64
    hand_size: int = 8
    queue_length_limit: int = 50
    exempt: bool = False
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"plc/{self.name}"

    @property
    def key(self) -> str:
        return self.name


# ------------------------------------------------------------------ Storage


@dataclass
class StorageClass:
    """storage.k8s.io/v1 — type StorageClass: provisioner + binding mode;
    drives dynamic provisioning in the volume binder."""

    name: str
    provisioner: str = ""  # "" = no dynamic provisioning
    volume_binding_mode: str = "Immediate"  # or "WaitForFirstConsumer"
    # zone restriction applied to dynamically provisioned PVs
    allowed_topology: Tuple[Tuple[str, str], ...] = ()
    # allowVolumeExpansion: bound claims may grow their request; the
    # expand controller resizes the backing PV (pkg/controller/volume/expand)
    allow_volume_expansion: bool = False
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"sc/{self.name}"

    @property
    def key(self) -> str:
        return self.name


# --------------------------------------------------- DRA structured parameters


@dataclass(frozen=True)
class DraDevice:
    """resource.k8s.io/v1 — type Device (basic): named device with string/num
    attributes and capacities."""

    name: str
    attributes: Tuple[Tuple[str, str], ...] = ()
    capacity: Tuple[Tuple[str, int], ...] = ()


@dataclass
class ResourceSlice:
    """resource.k8s.io/v1 — type ResourceSlice: the devices one driver
    publishes for one node."""

    name: str
    node_name: str = ""
    driver: str = ""
    pool: str = ""
    devices: Tuple[DraDevice, ...] = ()
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"slice/{self.name}"

    @property
    def key(self) -> str:
        return self.name


@dataclass(frozen=True)
class DeviceSelector:
    """CEL selector reduced to attribute equality / existence terms (ANDed):
    (key, value) with value "" meaning existence."""

    terms: Tuple[Tuple[str, str], ...] = ()

    def matches(self, dev: DraDevice) -> bool:
        attrs = dict(dev.attributes)
        for k, v in self.terms:
            if k not in attrs:
                return False
            if v and attrs[k] != v:
                return False
        return True


@dataclass
class DeviceClass:
    """resource.k8s.io/v1 — type DeviceClass: a named selector over devices."""

    name: str
    selector: DeviceSelector = field(default_factory=DeviceSelector)
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"dc/{self.name}"

    @property
    def key(self) -> str:
        return self.name


@dataclass
class ResourceClaim:
    """resource.k8s.io/v1 — type ResourceClaim, reduced to the DRA-lite
    model (counted devices of one DeviceClass — the schedulable core behind
    Pod.resource_claims).  Generated claims carry their owner pod's uid
    (resourceclaim controller: created from pod claim templates, reserved
    for the pod while it runs, released and deleted when it finishes —
    pkg/controller/resourceclaim/controller.go)."""

    name: str
    namespace: str = "default"
    device_class: str = ""
    count: int = 1
    owner_pod_uid: str = ""  # "" = user-created standalone claim
    reserved_for: Tuple[str, ...] = ()  # status.reservedFor pod uids
    allocated: bool = False  # status.allocation present
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"claim/{self.namespace}/{self.name}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class CertificateSigningRequest:
    """certificates.k8s.io/v1 — type CertificateSigningRequest: the kubelet
    serving/client certificate flow (cluster-scoped).  status: Pending ->
    Approved|Denied (approver policy) -> certificate issued (signer)."""

    name: str
    username: str = ""  # the requester (spec.username)
    groups: Tuple[str, ...] = ()
    signer_name: str = "kubernetes.io/kubelet-serving"
    usages: Tuple[str, ...] = ("digital signature", "server auth")
    status: str = "Pending"  # Pending | Approved | Denied
    certificate: str = ""  # status.certificate (issued by the signer)
    created_at: float = 0.0
    uid: str = ""

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"csr/{self.name}"

    @property
    def key(self) -> str:
        return self.name
