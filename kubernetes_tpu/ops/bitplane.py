"""Bit-packed mask planes + low-precision score storage — the packed data
plane (ROADMAP item 3b).

Boolean eligibility / validity / claim masks are stored as uint32 BIT-PLANE
WORDS along their node axis: `[..., N] bool` becomes `[..., W] uint32` with
`W = ceil(N / 32)`, bit `j` of word `k` holding node `k*32 + j`.  That cuts
the resident HBM of the `[P, N]` / `[U, N]` mask planes 8x (bool is a whole
byte on device) and shrinks every all-gather that ships them.  Raw score
planes (`traw` / `naraw` / `img` — normalize inputs) store as bf16 and are
upcast to f32 before every reduction (f32 accumulation), so the packed plane
changes BYTES, never DECISIONS.

SHARDED LAYOUT — per-shard-local word blocks: a mask sharded over `S` shards
of `nl` local nodes packs each shard's slice independently (`Wl =
ceil(nl/32)` words per shard), so the tiled `all_gather` along the word axis
concatenates shard blocks IN SHARD ORDER and the gathered `[.., S*Wl]` array
is exactly the packed form of the gathered dense mask.  Global node `g`
lives at shard `s = g // nl`, local bit `l = g % nl`, i.e. word
`s*Wl + l//32`, bit `l % 32` — `test_cols` below implements that map; with
`nl == N` (single device) it degenerates to the standard `ceil(N/32)`
layout.  TAIL-BIT RULE: bits past `nl` in a shard's last word are ALWAYS
zero (pack pads with False), so popcount / any-reductions never need a
separate tail mask.

Both knobs are TRACE-TIME constants (read once at import, baked into every
jit trace — the ops/tuning.py discipline, autotune sweeps run candidates in
fresh subprocesses):

  KTPU_PACK_MASKS=0    escape hatch back to dense bool planes
  KTPU_SCORE_DTYPE=f32 escape hatch back to f32 raw score storage

Decisions are bit-identical either way (tests/test_packed_masks.py); the
knobs trade HBM/collective bytes against a little shift/mask compute at the
unpack frontier.  The host-side mirrors (`np_*`, `bf16_round_np`) keep the
DeltaEncoder, the serial oracle and the native engine on the very same
quantization lattice, so decision parity against the oracle survives the
bf16 move by construction.
"""

from __future__ import annotations

import numpy as np

from . import tuning

# trace-time knobs (env > persisted autotune winner > default)
PACK_MASKS: bool = bool(int(tuning.tuned_knob("KTPU_PACK_MASKS", 1)))
SCORE_DTYPE: str = str(tuning.tuned_knob("KTPU_SCORE_DTYPE", "bf16"))
if SCORE_DTYPE not in ("bf16", "f32"):
    raise ValueError(
        f"KTPU_SCORE_DTYPE must be 'bf16' or 'f32', got {SCORE_DTYPE!r}"
    )

WORD_BITS = 32


def words_for(n: int) -> int:
    """Words per `n` mask bits: ceil(n / 32)."""
    return -(-int(n) // WORD_BITS)


# ---------------------------------------------------------------------------
# device side (jax) — imported lazily so host-only callers (encoder, oracle,
# native mirror) never touch a backend
# ---------------------------------------------------------------------------

def pack(x):
    """bool [..., n] -> uint32 [..., words_for(n)].  Tail bits (past n in the
    last word) are zero — pack pads with False, never garbage."""
    import jax.numpy as jnp

    n = x.shape[-1]
    w = words_for(n)
    pad = w * WORD_BITS - n
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros(x.shape[:-1] + (pad,), dtype=jnp.bool_)], axis=-1
        )
    # packbits first (8x reduction at the op level), then fold 4 bytes into
    # each little-endian word — the widest transient is dense/2 bytes, not
    # the 4x-dense a direct shift-and-reduce would materialize
    b = jnp.packbits(x, axis=-1, bitorder="little")  # uint8 [..., w*4]
    b = b.reshape(b.shape[:-1] + (w, 4)).astype(jnp.uint32)
    shift = jnp.arange(4, dtype=jnp.uint32) * jnp.uint32(8)
    return jnp.sum(b << shift, axis=-1, dtype=jnp.uint32)


def unpack(w, n: int):
    """uint32 [..., words_for(n)] -> bool [..., n] (the pack inverse)."""
    import jax.numpy as jnp

    shift = jnp.arange(4, dtype=jnp.uint32) * jnp.uint32(8)
    b = ((w[..., None] >> shift) & jnp.uint32(0xFF)).astype(jnp.uint8)
    bits = jnp.unpackbits(
        b.reshape(b.shape[:-2] + (-1,)), axis=-1, bitorder="little"
    )
    return bits[..., :n].astype(jnp.bool_)


def pack_blocks(x, s: int = 1):
    """bool [..., S*nl] -> uint32 [..., S*Wl] packed in PER-SHARD-LOCAL
    blocks: each of the `s` equal slices of the last axis packs
    independently, so sharding the word axis into `s` parts hands every
    shard exactly the packed form of its own node slice (the layout
    unpack_blocks / test_cols read).  s == 1 is plain pack()."""
    if s == 1:
        return pack(x)
    n = x.shape[-1] // s
    xb = x.reshape(x.shape[:-1] + (s, n))
    return pack(xb).reshape(x.shape[:-1] + (s * words_for(n),))


def unpack_blocks(w, nl: int):
    """uint32 [..., S*Wl] packed with PER-SHARD-LOCAL blocks of `nl` bits
    (the tiled all_gather layout) -> dense bool [..., S*nl].  Each shard
    block unpacks independently so the per-block pad bits (nl % 32 != 0)
    never leak into the dense view.  With one block (S == 1) this is
    exactly unpack(w, nl)."""
    wl = words_for(nl)
    s = w.shape[-1] // wl
    if s == 1:
        return unpack(w, nl)
    wb = w.reshape(w.shape[:-1] + (s, wl))
    return unpack(wb, nl).reshape(w.shape[:-1] + (s * nl,))


def test_cols(w, cols, nl: int):
    """Per-column bit test on a packed plane: `w[..., S*Wl]` packed with
    per-shard-local blocks of `nl` bits, `cols` int32 GLOBAL node ids in
    [0, S*nl).  Returns bool with shape w.shape[:-1] + cols.shape — the
    packed equivalent of `dense[..., cols]`.  With nl == N (unsharded /
    local view) the shard term vanishes."""
    import jax.numpy as jnp

    wl = words_for(nl)
    s, l = jnp.divmod(cols, nl)
    word = s * wl + l // WORD_BITS
    bit = (l % WORD_BITS).astype(jnp.uint32)
    return ((jnp.take(w, word, axis=-1) >> bit) & jnp.uint32(1)).astype(
        jnp.bool_
    )


def popcount(w, axis: int = -1):
    """Set-bit count along `axis` (int32) — exact because tail bits are
    zero by the pack rule."""
    import jax.numpy as jnp
    from jax import lax

    return jnp.sum(
        lax.population_count(w).astype(jnp.int32), axis=axis,
        dtype=jnp.int32,
    )


def any_bits(w, axis: int = -1):
    """Any bit set along `axis` — the packed `dense.any(axis)`."""
    return (w != 0).any(axis=axis)


def set_cols(w, cols, on, nl: int):
    """Packed scatter: set bit `cols[i]` to True where `on[i]`, on a packed
    [.., S*Wl] plane (duplicate columns are fine — OR semantics).  Routes
    through a transient dense [.., S*nl] plane: the scatter frontier is
    narrow (O(E) columns once per round), the RESIDENT form stays packed."""
    import jax.numpy as jnp

    n = (w.shape[-1] // words_for(nl)) * nl
    dense = jnp.zeros(w.shape[:-1] + (n + 1,), dtype=jnp.bool_)
    tgt = jnp.where(on, cols, n)
    dense = dense.at[..., tgt].set(True, mode="drop")
    return w | pack(dense[..., :n])


def assign_cols(w, cols, on, nl: int):
    """Packed column ASSIGNMENT: bit `cols[i]` := `on[..., i]` on a packed
    [.., S*Wl] plane — the patch-frontier sibling of set_cols (which only
    ORs).  `cols` are GLOBAL node ids in [0, S*nl]; ids == S*nl drop (the
    kernels' usual sentinel).  Duplicate columns must carry equal values
    (the callers' existing last-write-wins contract).  Routes through
    transient dense [.., S*nl] planes — the frontier is O(C) columns, the
    RESIDENT form stays packed."""
    import jax.numpy as jnp

    n = (w.shape[-1] // words_for(nl)) * nl
    tgt = jnp.clip(cols, 0, n)
    touched = jnp.zeros((n + 1,), dtype=jnp.bool_).at[tgt].set(True)[:n]
    newbits = (
        jnp.zeros(w.shape[:-1] + (n + 1,), dtype=jnp.bool_)
        .at[..., tgt].set(on, mode="drop")[..., :n]
    )
    if words_for(nl) * WORD_BITS == nl or w.shape[-1] == words_for(nl):
        tw = pack(touched)
        nw = pack(newbits)
    else:
        # per-shard blocks: pack each block independently (unpack_blocks
        # inverse) so block pad bits stay zero
        s = w.shape[-1] // words_for(nl)
        tw = pack(touched.reshape((s, nl))).reshape(-1)
        nw = pack(
            newbits.reshape(newbits.shape[:-1] + (s, nl))
        ).reshape(w.shape)
    return (w & ~tw) | nw


# ---------------------------------------------------------------------------
# score dtype (bf16 storage, f32 accumulation)
# ---------------------------------------------------------------------------

def score_store_dtype():
    """The jnp dtype raw score planes are STORED in (bf16 unless the
    KTPU_SCORE_DTYPE=f32 escape hatch is set).  Reductions always upcast to
    f32 first — grep for `.astype(jnp.float32)` at the consumers."""
    import jax.numpy as jnp

    return jnp.bfloat16 if SCORE_DTYPE == "bf16" else jnp.float32


def quantize_scores(x):
    """Device-side: round a computed f32 raw score plane onto the storage
    lattice (f32 -> bf16 keeps KTPU007 clean: never int -> bf16)."""
    return x.astype(score_store_dtype())


def np_score_dtype():
    """Host-side storage dtype (ml_dtypes ships with jax — no new dep)."""
    if SCORE_DTYPE == "bf16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(np.float32)


def quantize_scores_np(x: np.ndarray) -> np.ndarray:
    """Host-side mirror of quantize_scores (encoder-built planes)."""
    return np.asarray(x, dtype=np.float32).astype(np_score_dtype())


def bf16_round_np(x):
    """f32 -> storage lattice -> f32: the scalar/ndarray rounding the serial
    oracle and the native mirror apply to every raw score they compute, so
    their f32 values equal the device's upcast-from-storage values bit for
    bit.  Identity when KTPU_SCORE_DTYPE=f32."""
    if SCORE_DTYPE != "bf16":
        return np.float32(x) if np.isscalar(x) else np.asarray(x, np.float32)
    import ml_dtypes

    out = np.asarray(x, np.float32).astype(ml_dtypes.bfloat16).astype(
        np.float32
    )
    return np.float32(out) if out.ndim == 0 else out


# ---------------------------------------------------------------------------
# host side (numpy) — encoder transfer packing
# ---------------------------------------------------------------------------

def np_pack_lastaxis(a: np.ndarray) -> np.ndarray:
    """bool [..., n] -> uint32 [..., words_for(n)], same bit layout as
    pack() (little-endian bits within little-endian words — packbits
    bitorder='little' + a uint8->uint32 view on a little-endian host)."""
    a = np.ascontiguousarray(a, dtype=np.bool_)
    n = a.shape[-1]
    w = words_for(n)
    pad = w * WORD_BITS - n
    if pad:
        a = np.concatenate(
            [a, np.zeros(a.shape[:-1] + (pad,), dtype=np.bool_)], axis=-1
        )
    bytes_ = np.packbits(a, axis=-1, bitorder="little")
    return np.ascontiguousarray(bytes_).view(np.uint32)


def np_unpack_lastaxis(w: np.ndarray, n: int) -> np.ndarray:
    """uint32 [..., words] -> bool [..., n] (np_pack_lastaxis inverse)."""
    w = np.ascontiguousarray(w, dtype=np.uint32)
    bits = np.unpackbits(w.view(np.uint8), axis=-1, bitorder="little")
    return bits[..., :n].astype(np.bool_)
