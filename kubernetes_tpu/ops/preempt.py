"""Batched preemption — L8: victim search as masked rescoring on device.

reference: framework/preemption/preemption.go — type Evaluator +
defaultpreemption/default_preemption.go — SelectVictimsOnNode.  The CPU
evaluator (scheduler/plugins/cpu.py — DefaultPreemption, kept as the oracle)
walks nodes in Python and re-runs every Filter per reprieve step: O(nodes x
victims x plugins) interpreted work per failed pod.  Here the same semantics
run as ONE device program vectorized over the node axis:

  phase A  remove ALL lower-priority pods per node; feasibility =
           static row (taints/selector/nodename, from the cycle's encoded
           arrays) AND fit against (used - victims + nominated reservations)
  phase B  reprieve scan over victim slots (host supplies them in the CPU
           evaluator's exact order: PDB-violating first, then non-violating,
           each by (-priority, uid)): re-add slot j on every candidate node
           at once, keep it iff the preemptor still fits
  phase C  candidate stats for pickOneNodeForPreemption's lexicographic key
           (violations, max victim prio, prio sum, victim count, node index)
           — the host does the final argmin and the eviction

Scope gate (host side, scheduler/preemption.py): pods whose feasibility
depends on pairwise terms, host ports, or volume topology take the CPU
evaluator instead — removal-dependent pairwise state is per-candidate-node
and does not vectorize exactly.  The gate preserves behavior; the batched
path covers the fit-bound preemption that dominates at scale.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..api.snapshot import ClusterArrays
from . import filters


def _static_row(arr: ClusterArrays, pod_idx: jax.Array) -> jax.Array:
    """bool[N]: the preemptor's capacity-independent feasibility row — same
    terms as ops/assign.py — schedule_scan's `sf`, for one pod."""
    tm = filters.term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)  # [S, N]
    nodesel = filters.node_selection_ok_from(tm, arr)[pod_idx]  # [N]
    pin = arr.pod_nodename[pod_idx]
    my_nodes = jnp.arange(arr.N, dtype=jnp.int32)
    nodename_ok = jnp.where(pin == -1, True, pin == my_nodes)
    taints = filters.taints_ok(arr)[pod_idx]
    return arr.node_valid & nodesel & nodename_ok & taints


def _eval_body(
    arr: ClusterArrays,
    pod_idx: jax.Array,  # i32 scalar: the preemptor's row in arr
    used_now: jax.Array,  # i32[N, R] current per-node usage (scaled)
    nom_extra: jax.Array,  # i32[N, R] nominated reservations (scaled)
    has_nom: jax.Array,  # bool[N] nodes with >=1 relevant nominated pod
    vict_req: jax.Array,  # i32[N, V, R] victim requests (scaled), 0 pad
    vict_prio: jax.Array,  # i32[N, V] victim priorities
    vict_viol: jax.Array,  # bool[N, V] victim counted as PDB-violating
    vict_valid: jax.Array,  # bool[N, V]
) -> Tuple[jax.Array, ...]:
    """-> (cand[N], nvio[N], vmax[N], vsum[N], vcnt[N], is_victim[N, V],
    static_ok[N])."""
    req = arr.pod_req[pod_idx]  # [R]
    alloc = arr.node_alloc
    static_ok = _static_row(arr, pod_idx)

    removed = (vict_req * vict_valid[:, :, None]).sum(axis=1)  # [N, R]
    base = used_now + nom_extra - removed
    okA = static_ok & filters.fit_ok(req, base, alloc)  # all-removed

    def step(used_cur, xs):
        vr, valid = xs  # [N, R], [N]
        trial = used_cur + vr
        fits = filters.fit_ok(req, trial, alloc)  # preemptor still fits?
        keep = fits & valid & okA  # reprieved
        used_cur = jnp.where(keep[:, None], trial, used_cur)
        return used_cur, valid & okA & ~fits  # victim flag for this slot

    xs = (jnp.moveaxis(vict_req, 1, 0), jnp.moveaxis(vict_valid, 1, 0))
    used_final, victim_slots = lax.scan(step, base, xs)
    is_victim = jnp.moveaxis(victim_slots, 0, 1)  # [N, V]

    vcnt = is_victim.sum(axis=1)
    # second pass of the nominated two-pass filter: feasibility must not
    # DEPEND on a nominated pod that may never arrive (only checked when the
    # node has victims AND nominated pods — plugins/cpu.py:385)
    ok2 = jnp.where(
        has_nom & (vcnt > 0),
        filters.fit_ok(req, used_final - nom_extra, alloc),
        True,
    )
    nvio = (is_victim & vict_viol).sum(axis=1)
    neg_inf = jnp.iinfo(jnp.int32).min
    vmax = jnp.where(is_victim, vict_prio, neg_inf).max(axis=1)
    vsum = jnp.where(is_victim, vict_prio, 0).sum(axis=1)
    cand = okA & ok2 & (vcnt > 0)
    return cand, nvio, vmax, vsum, vcnt, is_victim, static_ok


# DELIBERATELY NON-DONATING (KTPU003 audit table, analysis/rules.py —
# AUDITED_NO_DONATE): every input is either the encoder's resident
# ClusterArrays or the priority-shared state snapshot (used_now / victim
# tables) that serves the whole same-priority wave and the host's
# sequential commit pass afterwards — donation would consume buffers the
# caller re-reads.  A no-op `donate_argnums=()` used to say this
# implicitly; the audit table says it out loud.
@jax.jit
def preempt_eval(*args) -> Tuple[jax.Array, ...]:
    """One preemptor (see _eval_body): -> (cand, nvio, vmax, vsum, vcnt,
    is_victim)."""
    return _eval_body(*args)[:6]


@jax.jit
def preempt_eval_wave(
    arr: ClusterArrays,
    pod_idxs: jax.Array,  # i32[K]: the wave's preemptor rows in arr
    used_now: jax.Array,
    nom_extra: jax.Array,
    has_nom: jax.Array,
    vict_req: jax.Array,
    vict_prio: jax.Array,
    vict_viol: jax.Array,
    vict_valid: jax.Array,
) -> Tuple[jax.Array, ...]:
    """Phases A-C for K SAME-PRIORITY preemptors against ONE shared state
    snapshot, in one device program: vmap over the preemptor axis only (the
    victim tables and usage are priority-shared, so everything else
    broadcasts and the per-node work batches [K, N] wide instead of looping
    K host round-trips).  K is the CALLER'S responsibility to bound: the
    program materializes ~K·N·V bytes of is_victim/slot-flag intermediates,
    so the host caps K to a byte budget instead of a fixed count
    (scheduler/preemption.py — _wave_cap, KTPU_PREEMPT_WAVE_BYTES).
    Returns [K, ...]-leading stats PLUS each
    preemptor's static feasibility row — the host's sequential commit pass
    re-derives exact per-node stats for nodes dirtied by earlier commits
    (scheduler/preemption.py — _host_node_stats), and the static row is the
    one state-independent input it cannot cheaply recompute."""
    return jax.vmap(
        _eval_body, in_axes=(None, 0, None, None, None, None, None, None, None)
    )(arr, pod_idxs, used_now, nom_extra, has_nom, vict_req, vict_prio,
      vict_viol, vict_valid)
