"""Persistent compilation cache + AOT warmup — killing the cold compile.

BENCH_r05 pays a 26.5 s XLA compile in EVERY process that touches the
north-star shape, because the jit cache dies with the process.  Two fixes
compose here:

  * `maybe_enable_compile_cache()` turns on JAX's persistent compilation
    cache (`jax.config.jax_compilation_cache_dir`) when
    ``KTPU_COMPILE_CACHE_DIR`` is set (or a path is passed explicitly).
    The first process to compile a (shape, config) writes the serialized
    executable; every later process — bench rounds, sidecar restarts,
    scheduler processes — loads it in seconds instead of recompiling.
    Thresholds are zeroed so the CPU sim caches too (the default config
    skips sub-second compiles, which would silently exclude smoke shapes
    from tests).
  * `warm_kernels()` is the explicit AOT path: ``kernel.lower(arr,
    cfg).compile()`` for the shapes a process is about to serve.  With the
    persistent cache enabled the compile both lands on disk and seeds this
    process's XLA cache, so the first REAL wave pays tracing only — warmup
    no longer needs a throwaway full run.

Both are wired into bench/harness.py, bench/matrix.py, bench.py and
scheduler/scheduler.py (mode="tpu").
"""

from __future__ import annotations

import os
from typing import Optional

_enabled_dir: Optional[str] = None


def maybe_enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Enable the persistent compilation cache at `path` (default: the
    ``KTPU_COMPILE_CACHE_DIR`` env var).  Returns the active cache dir, or
    None when no path is configured.  Idempotent; safe to call from every
    entry point — first caller wins, later conflicting paths raise (two
    halves of one process silently writing different caches would make
    "second process hits the cache" unfalsifiable)."""
    global _enabled_dir
    path = path or os.environ.get("KTPU_COMPILE_CACHE_DIR")
    if not path:
        return _enabled_dir
    if _enabled_dir is not None:
        if os.path.abspath(path) != _enabled_dir:
            raise ValueError(
                f"compile cache already enabled at {_enabled_dir!r}; "
                f"refusing to rebind to {path!r}"
            )
        return _enabled_dir
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # cache EVERYTHING: the defaults skip fast/small compiles, which would
    # exclude the smoke shapes tests assert on (and the CPU sim's smaller
    # programs) — the north-star entry is minutes either way
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = path
    return path


def compile_cache_dir() -> Optional[str]:
    """The active persistent-cache dir, or None."""
    return _enabled_dir


def warm_kernels(
    arr, cfg, *, gang: bool = False, ordinals: bool = True, batch: bool = True
) -> int:
    """AOT-compile the batch kernels for `arr`'s exact shape via
    ``lower().compile()`` — the explicit warmup path.  Returns the number
    of kernels compiled.  With the persistent cache enabled the
    executables land on disk, so a later process's first real call is a
    cache-hit load, not a recompile.

    Warms the VARIANTS the runtime actually routes — the donated kernels
    where the backend honors donation (the cache key includes the aliasing
    config, so warming the wrong variant saves nothing): the pipelined
    loop's schedule_batch (`batch`; pass False for callers that only drive
    the scheduler cycle — on TPU this kernel's compile is the minutes-class
    cost, so never pay it for an executable that won't run), the scheduler
    cycle's schedule_batch_ordinals (`ordinals`), and with `gang` the
    non-donating ordinals kernel the gang fixpoint re-invokes per iteration
    (ops/gang.py — schedule_with_gangs; donation is unsound there, the
    fixpoint re-reads its inputs)."""
    from .assign import (
        donation_supported,
        schedule_batch,
        schedule_batch_donated,
        schedule_batch_ordinals,
        schedule_batch_ordinals_donated,
    )

    import warnings

    donate = donation_supported()
    n = 0
    with warnings.catch_warnings():
        # expected on the donated variants: most inputs cannot alias the
        # two outputs (they still free early) — same policy as the routed
        # call wrappers in ops/assign.py
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        if batch:
            (schedule_batch_donated if donate else schedule_batch).lower(
                arr, cfg
            ).compile()
            n += 1
        if ordinals:
            (
                schedule_batch_ordinals_donated if donate
                else schedule_batch_ordinals
            ).lower(arr, cfg).compile()
            n += 1
        if gang and (donate or not ordinals):
            # not already covered above: the gang fixpoint always takes the
            # non-donating ordinals kernel
            schedule_batch_ordinals.lower(arr, cfg).compile()
            n += 1
    return n
