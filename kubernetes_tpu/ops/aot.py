"""Persistent compilation cache + AOT warmup — killing the cold compile.

BENCH_r05 pays a 26.5 s XLA compile in EVERY process that touches the
north-star shape, because the jit cache dies with the process.  Two fixes
compose here:

  * `maybe_enable_compile_cache()` turns on JAX's persistent compilation
    cache (`jax.config.jax_compilation_cache_dir`) when
    ``KTPU_COMPILE_CACHE_DIR`` is set (or a path is passed explicitly).
    The first process to compile a (shape, config) writes the serialized
    executable; every later process — bench rounds, sidecar restarts,
    scheduler processes — loads it in seconds instead of recompiling.
    Thresholds are zeroed so the CPU sim caches too (the default config
    skips sub-second compiles, which would silently exclude smoke shapes
    from tests).
  * `warm_kernels()` is the explicit AOT path: ``kernel.lower(arr,
    cfg).compile()`` for the shapes a process is about to serve.  With the
    persistent cache enabled the compile both lands on disk and seeds this
    process's XLA cache, so the first REAL wave pays tracing only — warmup
    no longer needs a throwaway full run.

Both are wired into bench/harness.py, bench/matrix.py, bench.py and
scheduler/scheduler.py (mode="tpu").
"""

from __future__ import annotations

import os
from typing import Optional

from .. import chaos

_enabled_dir: Optional[str] = None

# a crash mid-write truncates an entry to 0 or a few bytes — below any
# compressed executable's compression header, let alone its payload
_MIN_ENTRY_BYTES = 8


def scrub_compile_cache(path: Optional[str] = None, aggressive: bool = False) -> int:
    """Remove unreadably-corrupt entries from the persistent compile cache;
    returns how many files were dropped.  The cheap pass drops empty and
    sub-magic-sized files (a crash mid-write truncates to 0 or a few
    bytes); aggressive=True (the post-compile-failure path, where SOME
    entry provably poisoned the load but XLA does not say which) drops
    every cache entry — the fresh compiles that follow rewrite them.
    Either way the contract holds: a corrupt entry costs a recompile,
    never a crash out of warmup."""
    path = path or _enabled_dir
    if not path or not os.path.isdir(path):
        return 0
    dropped = 0
    for name in os.listdir(path):
        fp = os.path.join(path, name)
        if not os.path.isfile(fp):
            continue
        try:
            if aggressive:
                os.remove(fp)
                dropped += 1
                continue
            size = os.path.getsize(fp)
            if size < _MIN_ENTRY_BYTES:
                os.remove(fp)
                dropped += 1
        except OSError:
            continue  # raced with a concurrent writer: its entry is fresh
    return dropped


def maybe_enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Enable the persistent compilation cache at `path` (default: the
    ``KTPU_COMPILE_CACHE_DIR`` env var).  Returns the active cache dir, or
    None when no path is configured.  Idempotent; safe to call from every
    entry point — first caller wins, later conflicting paths raise (two
    halves of one process silently writing different caches would make
    "second process hits the cache" unfalsifiable)."""
    global _enabled_dir
    path = path or os.environ.get("KTPU_COMPILE_CACHE_DIR")
    if not path:
        return _enabled_dir
    if _enabled_dir is not None:
        if os.path.abspath(path) != _enabled_dir:
            raise ValueError(
                f"compile cache already enabled at {_enabled_dir!r}; "
                f"refusing to rebind to {path!r}"
            )
        return _enabled_dir
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    # drop obviously-truncated entries BEFORE jax ever reads the dir (a
    # previous process crashing mid-write must cost a recompile, not an
    # exception out of the first warmup)
    scrub_compile_cache(path)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # cache EVERYTHING: the defaults skip fast/small compiles, which would
    # exclude the smoke shapes tests assert on (and the CPU sim's smaller
    # programs) — the north-star entry is minutes either way
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled_dir = path
    return path


def compile_cache_dir() -> Optional[str]:
    """The active persistent-cache dir, or None."""
    return _enabled_dir


def warm_kernels(
    arr, cfg, *, gang: bool = False, ordinals: bool = True, batch: bool = True
) -> int:
    """AOT-compile the batch kernels for `arr`'s exact shape via
    ``lower().compile()`` — the explicit warmup path.  Returns the number
    of kernels compiled.  With the persistent cache enabled the
    executables land on disk, so a later process's first real call is a
    cache-hit load, not a recompile.

    Warms the VARIANTS the runtime actually routes — the donated kernels
    where the backend honors donation (the cache key includes the aliasing
    config, so warming the wrong variant saves nothing): the pipelined
    loop's schedule_batch (`batch`; pass False for callers that only drive
    the scheduler cycle — on TPU this kernel's compile is the minutes-class
    cost, so never pay it for an executable that won't run), the scheduler
    cycle's schedule_batch_ordinals (`ordinals`), and with `gang` the
    non-donating ordinals kernel the gang fixpoint re-invokes per iteration
    (ops/gang.py — schedule_with_gangs; donation is unsound there, the
    fixpoint re-reads its inputs)."""
    from .assign import (
        donation_supported,
        schedule_batch,
        schedule_batch_donated,
        schedule_batch_ordinals,
        schedule_batch_ordinals_donated,
    )

    import warnings

    if chaos.enabled():
        fault = chaos.poke("compile.cache")
        if fault is not None and fault.action == "corrupt":
            _corrupt_one_cache_entry()

    donate = donation_supported()
    n = 0
    with warnings.catch_warnings():
        # expected on the donated variants: most inputs cannot alias the
        # two outputs (they still free early) — same policy as the routed
        # call wrappers in ops/assign.py
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        if batch:
            _compile_with_cache_recovery(
                schedule_batch_donated if donate else schedule_batch, arr, cfg
            )
            n += 1
        if ordinals:
            _compile_with_cache_recovery(
                schedule_batch_ordinals_donated if donate
                else schedule_batch_ordinals,
                arr, cfg,
            )
            n += 1
        if gang and (donate or not ordinals):
            # not already covered above: the gang fixpoint always takes the
            # non-donating ordinals kernel
            _compile_with_cache_recovery(schedule_batch_ordinals, arr, cfg)
            n += 1
    return n


def _compile_with_cache_recovery(kernel, arr, cfg) -> None:
    """lower().compile() that survives a corrupt persistent-cache entry.

    Classification by experiment, not guesswork: on failure with the cache
    enabled, retry ONCE with the persistent cache disabled.  If that also
    fails, the error is a genuine compile error — re-raise with the shared
    cache dir UNTOUCHED (wiping valid entries other processes depend on
    would fix nothing).  If it succeeds, the on-disk entry is what poisoned
    the load: scrub the dir aggressively and compile again with the cache
    re-enabled so the fresh write IS the repair.  Either way warmup never
    dies to a truncated file on disk."""
    try:
        kernel.lower(arr, cfg).compile()
        return
    except Exception:  # noqa: BLE001 — classify below, re-raise when real
        if _enabled_dir is None:
            raise
    import time

    import jax

    t0 = time.perf_counter()
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        kernel.lower(arr, cfg).compile()  # genuine error still raises here
    finally:
        jax.config.update("jax_compilation_cache_dir", _enabled_dir)
    scrub_compile_cache(_enabled_dir, aggressive=True)
    kernel.lower(arr, cfg).compile()  # cache-enabled: rewrites fresh entries
    chaos.record_recovery("compile.cache", "recompile", start=t0)


def _corrupt_one_cache_entry() -> None:
    """The compile.cache chaos action: truncate the first cache entry to
    garbage — exactly the artifact of a process killed mid-write."""
    d = _enabled_dir
    if not d or not os.path.isdir(d):
        return
    for name in sorted(os.listdir(d)):
        fp = os.path.join(d, name)
        if os.path.isfile(fp):
            with open(fp, "wb") as f:
                f.write(b"\x00bad")
            return
