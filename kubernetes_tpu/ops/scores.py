"""Score (Score extension point) kernels — L2.

Replaces the reference's second parallelize.Until fan-out (pkg/scheduler/
schedule_one.go — prioritizeNodes; framework/runtime/framework.go —
RunScorePlugins) with elementwise array math.

Score arithmetic is float32 (the oracle mirrors it op-for-op, so TPU-vs-oracle
parity is exact); the reference computes in int64 — a documented deviation that
can differ only when an int division truncates within one f32 ulp of a score
boundary.  MaxNodeScore = 100 (framework/interface.go — MaxNodeScore).

Per-pod normalization (NormalizeScore) runs over the pod's *currently feasible*
node set, which depends on capacity state — so the normalize+weight step happens
inside the commit scan (ops/assign.py) on [N]-shaped slices, while raw
per-(pod,node) counts are batched here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from ..api.snapshot import ClusterArrays

MAX_NODE_SCORE = 100.0


@dataclass(frozen=True)
class ScoreConfig:
    """Default-profile plugin weights (reference: pkg/scheduler/apis/config/v1/
    default_plugins.go — getDefaultPlugins multipoint weights) and the scored
    resource axis indices (cpu, memory — noderesources defaults)."""

    fit_weight: float = 1.0  # NodeResourcesFit score weight
    # NodeResourcesFitArgs.scoringStrategy (noderesources/fit.go):
    # LeastAllocated (default) | MostAllocated | RequestedToCapacityRatio
    fit_strategy: str = "LeastAllocated"
    # RequestedToCapacityRatio shape points as (utilization%, score) pairs,
    # linearly interpolated (requested_to_capacity_ratio.go —
    # buildRequestedToCapacityRatioScorerFunction); must be sorted by
    # utilization.  Scores are in [0, 10] in the reference's shape and are
    # rescaled to MaxNodeScore by the scorer.
    rtcr_shape: Tuple[Tuple[float, float], ...] = ((0.0, 0.0), (100.0, 10.0))
    balanced_weight: float = 1.0  # NodeResourcesBalancedAllocation
    taint_weight: float = 3.0  # TaintToleration
    node_affinity_weight: float = 2.0  # NodeAffinity (preferred terms)
    spread_weight: float = 2.0  # PodTopologySpread
    interpod_weight: float = 2.0  # InterPodAffinity
    # InterPodAffinityArgs.hardPodAffinityWeight: existing pods' REQUIRED
    # affinity terms toward the incoming pod score at this weight (default 1)
    hard_pod_affinity_weight: float = 1.0
    image_weight: float = 1.0  # ImageLocality
    score_resources: Tuple[int, ...] = (0, 1)  # indices into the R axis
    # Static specialization: when a snapshot carries no pairwise terms / host
    # ports, the jitted program omits that per-step state entirely (XLA sees
    # the branch at trace time).  Results are identical either way; this only
    # prunes provably-dead work.  See infer_score_config.
    enable_pairwise: bool = True
    enable_ports: bool = True
    # Prune the [P, N] taint-score / preferred-node-affinity matrices when no
    # PreferNoSchedule taint / preferred term exists: their contribution is a
    # constant (or zero) per pod, which cannot change argmax.
    enable_taint_score: bool = True
    enable_node_pref: bool = True
    enable_image: bool = True
    enable_interpod_score: bool = True  # preferred (soft) inter-pod affinity


DEFAULT_SCORE_CONFIG = ScoreConfig()


def infer_score_config(arr, cfg: ScoreConfig = DEFAULT_SCORE_CONFIG) -> ScoreConfig:
    """Specialize cfg to the snapshot: disable pairwise/ports stages the
    encoded arrays prove unused (host-side inspection of concrete arrays)."""
    import dataclasses

    import numpy as np

    has_terms = bool(
        np.any(arr.pod_aff_terms >= 0)
        or np.any(arr.pod_anti_terms >= 0)
        or np.any(arr.pod_spread_terms >= 0)
        or np.any(arr.anti_counts0 > 0)
    )
    has_ports = bool(np.any(arr.pod_ports) or np.any(arr.node_ports0))
    has_prefer_taints = bool(np.any(arr.node_taint_pref))
    has_node_pref = bool(np.any(arr.pod_pref_terms >= 0))
    has_image = arr.image_score.shape[1] == arr.N and bool(np.any(arr.image_score))
    has_interpod_pref = bool(
        np.any(arr.pod_pref_aff_terms >= 0)
        or np.any(arr.pref_own0 != 0)
        # committed pods' REQUIRED affinity terms score toward later pods
        # at hardPodAffinityWeight, so required terms alone need the stage
        or (cfg.hard_pod_affinity_weight > 0 and np.any(arr.pod_aff_terms >= 0))
    )
    return dataclasses.replace(
        cfg,
        enable_pairwise=has_terms or has_interpod_pref,
        enable_ports=has_ports,
        enable_taint_score=has_prefer_taints,
        enable_node_pref=has_node_pref,
        enable_image=has_image,
        enable_interpod_score=has_interpod_pref,
    )


def least_allocated(
    requested: jax.Array, alloc: jax.Array, res_idx: Tuple[int, ...]
) -> jax.Array:
    """f32[N]: NodeResourcesFit LeastAllocated strategy.

    reference: noderesources/least_allocated.go — leastResourceScorer:
    score_r = max(0, (alloc - requested) * 100 / alloc), 0 when alloc == 0;
    node score = mean over scored resources (equal resource weights).
    """
    idx = jnp.array(res_idx, dtype=jnp.int32)
    a = alloc[:, idx].astype(jnp.float32)
    r = requested[:, idx].astype(jnp.float32)
    per_res = jnp.where(a > 0, jnp.maximum(0.0, (a - r) * MAX_NODE_SCORE / a), 0.0)
    return per_res.mean(axis=1)


def most_allocated(
    requested: jax.Array, alloc: jax.Array, res_idx: Tuple[int, ...]
) -> jax.Array:
    """f32[N]: NodeResourcesFit MostAllocated strategy (bin-packing).

    reference: noderesources/most_allocated.go — mostResourceScorer:
    score_r = requested * 100 / alloc; 0 when alloc == 0 OR requested
    exceeds alloc (the reference returns 0 for over-capacity rather than
    clamping); node score = mean over scored resources."""
    idx = jnp.array(res_idx, dtype=jnp.int32)
    a = alloc[:, idx].astype(jnp.float32)
    r = requested[:, idx].astype(jnp.float32)
    per_res = jnp.where(
        (a > 0) & (r <= a),
        r * MAX_NODE_SCORE / jnp.where(a > 0, a, 1.0),
        0.0,
    )
    return per_res.mean(axis=1)


def interp_shape_f32(util: jax.Array, shape) -> jax.Array:
    """Piecewise-linear interpolation through the RTCR shape points with ONE
    EXPLICIT float32 op order — y0 + t*(y1-y0), t = (u-x0)/(x1-x0) — mirrored
    verbatim by the oracle (_rtcr) and the C++ engine (interp_shape), so all
    three engines agree bit-for-bit (np.interp/jnp.interp would each use
    their own internal precision/op order).  Clamps outside the shape."""
    xs = [jnp.float32(p[0]) for p in shape]
    ys = [jnp.float32(p[1]) for p in shape]
    out = jnp.full_like(util, ys[-1])
    # descending so the FIRST matching segment wins (strictly increasing xs
    # are enforced by config validation)
    for i in range(len(xs) - 1, 0, -1):
        t = (util - xs[i - 1]) / (xs[i] - xs[i - 1])
        seg = ys[i - 1] + t * (ys[i] - ys[i - 1])
        out = jnp.where(util <= xs[i], seg, out)
    return jnp.where(util <= xs[0], ys[0], out)


def requested_to_capacity_ratio(
    requested: jax.Array,
    alloc: jax.Array,
    res_idx: Tuple[int, ...],
    shape: Tuple[Tuple[float, float], ...],
) -> jax.Array:
    """f32[N]: NodeResourcesFit RequestedToCapacityRatio strategy.

    reference: noderesources/requested_to_capacity_ratio.go — the scorer
    linearly interpolates the utilization%% (requested*100/alloc) through the
    user's shape points (scores 0..10), then rescales to MaxNodeScore;
    utilization outside the shape clamps to the end points.  capacity == 0
    scores as 100%% utilization (resourceScoringFunction returns
    rawScoringFunction(maxUtilization)), not 0 — mirrored by the oracle and
    the C++ engine."""
    idx = jnp.array(res_idx, dtype=jnp.int32)
    a = alloc[:, idx].astype(jnp.float32)
    r = requested[:, idx].astype(jnp.float32)
    util = jnp.where(a > 0, r * 100.0 / jnp.where(a > 0, a, 1.0), 100.0)
    score10 = interp_shape_f32(util, shape)
    per_res = score10 * (MAX_NODE_SCORE / 10.0)
    return per_res.mean(axis=1)


FIT_STRATEGIES = ("LeastAllocated", "MostAllocated", "RequestedToCapacityRatio")


def fit_score(
    requested: jax.Array,
    alloc: jax.Array,
    cfg: "ScoreConfig",
) -> jax.Array:
    """NodeResourcesFit's Score, dispatched on the profile's scoringStrategy
    at trace time (cfg is static under jit).  Unknown strategies raise —
    every engine fails the same way instead of silently scoring with the
    default."""
    if cfg.fit_strategy == "MostAllocated":
        return most_allocated(requested, alloc, cfg.score_resources)
    if cfg.fit_strategy == "RequestedToCapacityRatio":
        return requested_to_capacity_ratio(
            requested, alloc, cfg.score_resources, cfg.rtcr_shape
        )
    if cfg.fit_strategy != "LeastAllocated":
        raise ValueError(f"unknown fit scoringStrategy {cfg.fit_strategy!r}")
    return least_allocated(requested, alloc, cfg.score_resources)


def balanced_allocation(
    requested: jax.Array, alloc: jax.Array, res_idx: Tuple[int, ...]
) -> jax.Array:
    """f32[N]: NodeResourcesBalancedAllocation.

    reference: noderesources/balanced_allocation.go — balancedResourceScorer:
    fractions f_r = min(1, requested/alloc) over resources with alloc > 0;
    score = (1 - std(f)) * 100 with population std over present resources.
    """
    idx = jnp.array(res_idx, dtype=jnp.int32)
    a = alloc[:, idx].astype(jnp.float32)
    r = requested[:, idx].astype(jnp.float32)
    present = a > 0
    f = jnp.where(present, jnp.minimum(1.0, r / jnp.where(present, a, 1.0)), 0.0)
    cnt = jnp.maximum(1, present.sum(axis=1)).astype(jnp.float32)
    mean = f.sum(axis=1) / cnt
    var = (jnp.where(present, (f - mean[:, None]) ** 2, 0.0)).sum(axis=1) / cnt
    return (1.0 - jnp.sqrt(var)) * MAX_NODE_SCORE


def taint_prefer_counts(arr: ClusterArrays) -> jax.Array:
    """[P, N] # of intolerable PreferNoSchedule taints — TaintToleration's
    raw Score before normalization (tainttoleration/taint_toleration.go —
    CountIntolerableTaintsPreferNoSchedule).

    Computed in f32 (counting matmul, exact < 2^24), STORED on the
    bf16 lattice (ops/bitplane.py — KTPU_SCORE_DTYPE): the resident raw
    plane is a normalize input, and the serial oracle / native engine round
    through the same lattice, so decisions stay bit-identical.  Consumers
    upcast to f32 before reducing."""
    from . import bitplane

    return bitplane.quantize_scores(
        jnp.einsum(
            "pt,nt->pn",
            (~arr.pod_tol_pref).astype(jnp.float32),
            arr.node_taint_pref.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )
    )


def normalize_reverse(counts: jax.Array, feasible: jax.Array) -> jax.Array:
    """f32[N]: DefaultNormalizeScore with reverse=true over the feasible set.

    reference: framework/plugins/helper/normalize_score.go: score_i =
    max - max * count_i / maxCount; all `max` when maxCount == 0.
    """
    max_c = jnp.max(jnp.where(feasible, counts, 0.0))
    return jnp.where(
        max_c > 0, MAX_NODE_SCORE - MAX_NODE_SCORE * counts / max_c, MAX_NODE_SCORE
    )
