"""Gang scheduling: all-or-nothing PodGroups — BASELINE config 5.

Analog of the reference ecosystem's coscheduling plugin (PodGroup CRD +
Permit-based waiting; the in-tree precedent is the Permit extension point,
framework/runtime/waiting_pods_map.go): a group binds only if at least
minMember of its pods can be placed in this cycle.

Batch formulation: run the commit scan optimistically; if any group missed its
quorum, revoke ONE failed group — the earliest in activeQ order — and re-run,
because its freed capacity may let later gangs (which only failed by transient
contention) succeed.  Revoking one at a time mirrors the reference timeline:
a gang whose Permit times out is rejected back to the backoff queue, and the
remaining pods reschedule against the released capacity.  Revoked groups stay
revoked within the cycle.  <= #groups + 1 scans, all hitting the same compiled
executable (shapes never change).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..api.snapshot import ClusterArrays
from .scores import ScoreConfig


def failed_groups(choices: np.ndarray, pod_group: np.ndarray, group_min: np.ndarray,
                  active: Optional[np.ndarray] = None) -> np.ndarray:
    """bool[G]: groups (with >=1 active pod) that missed their quorum."""
    G = group_min.shape[0]
    sched = np.zeros(G, dtype=np.int64)
    present = np.zeros(G, dtype=bool)
    mask = pod_group >= 0
    if active is not None:
        mask &= active
    np.add.at(sched, pod_group[mask], (choices[mask] >= 0).astype(np.int64))
    present[pod_group[mask]] = True
    return present & (sched < group_min)


def schedule_with_gangs(
    arr: ClusterArrays, cfg: ScoreConfig, with_ordinals: bool = False,
    mesh=None, inc=None,
):
    """Schedule honoring all-or-nothing groups.

    Returns (choices i32[P] with revoked gangs at -1, node_used i32[N, R]);
    with_ordinals appends (ordinals, sweeps): per-pod commit ordinals
    positioned AFTER the earlier fixpoint iterations' sweeps (a pod's
    decision is only available once the final program ran), with `sweeps`
    the total across all iterations — see assign.schedule_batch_ordinals.

    `mesh` runs each fixpoint iteration's batch step node-axis SHARDED
    (parallel/sharded.py) — safe here because the host fixpoint never
    donates (it re-reads `arr` across iterations), and decision-identical
    since each iteration is an ordinary routed batch call.

    `inc` (ops/incremental.py) is safe to reuse across fixpoint iterations:
    the only per-iteration change is pod_valid, which the resident class
    state deliberately excludes (the kernels fold validity per pod), and a
    revocation masks whole equivalence classes — pod_group is part of the
    spec key — so class-row consistency holds at every iteration.  The
    class-batched commit-wave stage (assign._wave_commit_stage) therefore
    rides each fixpoint iteration unchanged: it reads only the shared
    IncState rows plus the iteration's pod_valid, and the sweeps_prior
    offset below keeps the returned ordinals a single global commit order
    across iterations exactly as for the round loop."""
    from .assign import (
        schedule_batch_ordinals_routed,
        schedule_batch_routed,
    )

    pod_valid = np.asarray(arr.pod_valid).copy()
    revoked = np.zeros_like(pod_valid)
    sweeps_prior = 0
    while True:
        arr_i = dataclasses.replace(arr, pod_valid=pod_valid)
        if with_ordinals:
            choices, used, ords, sweeps = schedule_batch_ordinals_routed(
                arr_i, cfg, donate=False, mesh=mesh, inc=inc
            )
        else:
            choices, used = schedule_batch_routed(
                arr_i, cfg, donate=False, mesh=mesh, inc=inc
            )
        choices = np.asarray(choices)
        pod_group = np.asarray(arr.pod_group)
        bad = failed_groups(choices, pod_group, np.asarray(arr.group_min), active=pod_valid)
        if not bad.any():
            if with_ordinals:
                return (choices, np.asarray(used),
                        np.asarray(ords) + sweeps_prior,
                        sweeps_prior + int(sweeps))
            return choices, np.asarray(used)
        if with_ordinals:
            sweeps_prior += int(sweeps)
        # revoke the failed group appearing earliest in activeQ order
        in_bad = bad[np.maximum(pod_group, 0)] & (pod_group >= 0) & pod_valid
        first_g = pod_group[int(np.argmax(in_bad))]
        newly = (pod_group == first_g) & pod_valid
        revoked |= newly
        pod_valid = pod_valid & ~newly


@partial(jax.jit, static_argnames=("cfg",))
def gang_fixpoint_device(
    arr: ClusterArrays, cfg: ScoreConfig
) -> Tuple[jax.Array, jax.Array]:
    """schedule_with_gangs as ONE device program: the revoke-one fixpoint
    runs inside a `lax.while_loop` (body = full commit scan + quorum check
    + earliest-failed-group revocation), so a gang wave DISPATCHES
    asynchronously exactly like a non-gang wave — the sidecar can release
    its device lock after dispatch and read the verdicts back outside it
    (round-4 verdict missing #5: config 5 previously blocked the lock
    through every host-side fixpoint round-trip).

    Decision-identical to the host loop (tests/test_gang.py — device
    fixpoint parity): the same kernel routing serves each iteration (the
    routing predicates are trace-time static), the quorum counts are the
    same integer scatter-adds, and the revoked group is the one whose
    first pod index is lowest — `argmax` over the in-bad mask matches the
    host's np.argmax tie-break.  Bounded by #groups + 1 iterations, all
    inside one compiled executable (shapes never change across
    iterations)."""
    from .assign import schedule_batch_impl

    pod_group = arr.pod_group
    group_min = arr.group_min
    G = group_min.shape[0]
    P = arr.P
    if G == 0:  # trace-time static: no groups -> plain batch
        return schedule_batch_impl(arr, cfg)

    from .scopes import subphase

    def body(carry):
        pv, _, _, _ = carry
        arr_i = dataclasses.replace(arr, pod_valid=pv)
        choices, used = schedule_batch_impl(arr_i, cfg)
        # quorum count + earliest-failed-group revocation = this iteration's
        # commit disposition (the kernel interior carries its own sub-phases)
        with subphase("commit"):
            mask = (pod_group >= 0) & pv
            gidx = jnp.where(mask, pod_group, G)  # G = drop sentinel
            sched = jnp.zeros(G, dtype=jnp.int32).at[gidx].add(
                (choices >= 0).astype(jnp.int32), mode="drop"
            )
            present = jnp.zeros(G, dtype=bool).at[gidx].set(True, mode="drop")
            bad = present & (sched < group_min)
            anybad = bad.any()
            in_bad = bad[jnp.maximum(pod_group, 0)] & (pod_group >= 0) & pv
            first_g = pod_group[jnp.argmax(in_bad)]
            newly = (pod_group == first_g) & pv
            pv_next = jnp.where(anybad, pv & ~newly, pv)
            return pv_next, choices, used, ~anybad

    init = (
        arr.pod_valid,
        jnp.full((P,), -1, dtype=jnp.int32),
        jnp.zeros_like(arr.node_used),
        jnp.array(False),
    )
    _, choices, used, _ = lax.while_loop(
        lambda c: ~c[3], body, init
    )
    return choices, used
