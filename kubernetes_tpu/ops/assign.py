"""Batched assignment with sequential-commit semantics — L3 (the hard part).

The reference schedules one pod per cycle; placing pod i mutates NodeInfo before
pod i+1 is considered (pkg/scheduler/schedule_one.go — ScheduleOne + the assume
cache, backend/cache/cache.go — AssumePod).  To reproduce those semantics in one
XLA program, everything capacity-independent (static feasibility, raw score
counts, selector matmuls) is evaluated for the whole batch up front as [P, N]
matrices, and a `lax.scan` over pods (in activeQ order == array order)
re-evaluates only the state-dependent terms per step:

  - NodeResourcesFit.Filter against the running node_used
  - NodePorts.Filter against the running ports_used
  - PodTopologySpread / InterPodAffinity against running PER-NODE count state
    cnt_node/anti_node/pref_node[T, N] (committed pods become "existing" for
    every later pod — including their own anti-affinity terms; see
    ops/pairwise.py for why the state is per-node rather than per-domain)
  - LeastAllocated / BalancedAllocation scores against used + this pod's request
  - per-pod NormalizeScore over the *currently* feasible set

Host selection is argmax of the weighted sum; ties break to the lowest node
index.  (The reference's selectHost — schedule_one.go — picks randomly among
equal-score nodes; this framework is deterministic by design, the "full-scoring
deterministic mode" deviation called out in SURVEY.md §7 hard part 1.  The
oracle applies the identical rule, so parity is exact within the framework.)

ONE implementation serves both execution modes: `axis_name=None` runs on a
single device; under shard_map (parallel/sharded.py) the same step function
sees local node shards and stitches global decisions with pmax/pmin/psum —
per-node score math never crosses shards, so both modes are bit-identical.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..api.snapshot import ClusterArrays
from . import bitplane, filters, pairwise, tuning
from .scopes import subphase as _subphase
from .scores import (
    MAX_NODE_SCORE,
    ScoreConfig,
    balanced_allocation,
    fit_score,
    taint_prefer_counts,
)

_INT_MAX = jnp.iinfo(jnp.int32).max


def _rmax(x, axis_name):
    """Reduce-max over the node axis (last), then across shards if sharded."""
    m = jnp.max(x, axis=-1)
    return lax.pmax(m, axis_name) if axis_name else m


def _rmin(x, axis_name):
    m = jnp.min(x, axis=-1)
    return lax.pmin(m, axis_name) if axis_name else m


def _gather_cols(x, cols, axis_name, base, local_n):
    """x[:, cols] for a node-axis-sharded x [C, local_n] and GLOBAL column ids
    cols [D] -> [D]-column matrix [C, D], replicated: the owner shard
    contributes its columns, psum broadcasts.  Zero-fill is exact — exactly
    one shard owns each id, and v + 0 == v for every finite v and ±inf."""
    if not axis_name:
        return jnp.take_along_axis(x, cols[None, :], axis=1)
    isbool = x.dtype == jnp.bool_
    xv = x.astype(jnp.int32) if isbool else x
    mine = (cols >= base) & (cols < base + local_n)
    lc = jnp.where(mine, cols - base, 0)
    v = jnp.take_along_axis(xv, lc[None, :], axis=1)
    v = jnp.where(mine[None, :], v, 0)
    out = lax.psum(v, axis_name)
    return out > 0 if isbool else out


def _gather_at_nodes(x, rows, nodes, axis_name, base, local_n):
    """x[rows, nodes] for a node-axis-sharded x [T, local_n] and GLOBAL node
    ids — the owner-shard psum broadcast (same pattern as schedule_scan's
    committed-domain column)."""
    if not axis_name:
        return x[rows, nodes]
    mine = (nodes >= base) & (nodes < base + local_n)
    v = jnp.where(mine, x[rows, jnp.where(mine, nodes - base, 0)], 0)
    return lax.psum(v, axis_name)


def _global_top_k(vals, k, axis_name, base):
    """lax.top_k over the GLOBAL node axis of a node-axis-sharded [C, local_n]
    array -> (values [C, k], GLOBAL ids [C, k]), bit-identical — values, ids,
    order, lowest-index ties — to single-device top_k on the concatenation:
    an entry outside its shard's local top-k has >= k better-or-equal-ranked
    entries in that shard alone, so it cannot rank globally; shard-local
    lists keep equal values in ascending local-index order and the all_gather
    concatenates in shard order (= ascending global index), so the merge's
    lowest-position tie-break IS the lowest-global-index tie-break."""
    if not axis_name:
        return lax.top_k(vals, k)
    kl = min(k, vals.shape[-1])
    lv, li = lax.top_k(vals, kl)
    av = lax.all_gather(lv, axis_name, axis=1, tiled=True)  # [C, S*kl]
    ai = lax.all_gather(li + base, axis_name, axis=1, tiled=True)
    mv, mp = lax.top_k(av, k)
    return mv, jnp.take_along_axis(ai, mp, axis=1)


def pod_unshard(arr: ClusterArrays, inc=None, axis_name: str = "pods"):
    """Entry stage of every kernel on a 2-D pods x nodes mesh: stitch the
    pod-shard-local resident blocks back to full pod extent with ONE tiled
    all_gather per pod-sharded field (axis positions from the rule table —
    parallel/partition_rules.pod_axis_fields), then the existing kernels run
    verbatim with their node-axis collectives.

    Residency is where the 2-D win lives (the KTPU015 replicated-giant set
    shards at rest and over the wire on placement); the gathered copies are
    program transients, priced honestly by shard_hbm_estimate's
    ``pod_gather`` term.  The gathers are UNCONDITIONAL and first — before
    any cond/scan — so the per-shard collective sequence stays a pure
    function of the route (KTPU009) and bit-identity vs the serial oracle
    is by construction: every pod-row of the mesh computes the identical
    full-pod program on identical node shards.

    Returns (arr, inc) with full pod axes; ``inc`` (ops/incremental.py)
    gathers only its pod-aligned ``cls`` vector — the [U, *] class matrices
    are class-aligned and never pod-sharded."""
    import dataclasses

    from ..parallel.partition_rules import pod_axis_fields

    fields = dict(pod_axis_fields())
    fields["image_score"] = (0, 0)  # both [P, N] and [P, 1] forms
    repl = {
        name: lax.all_gather(
            getattr(arr, name), axis_name, axis=axis, tiled=True
        )
        for name, (axis, _fill) in sorted(fields.items())
    }
    arr = dataclasses.replace(arr, **repl)
    if inc is not None:
        inc = inc._replace(
            cls=lax.all_gather(inc.cls, axis_name, axis=0, tiled=True)
        )
    return arr, inc


def _preferred_node_affinity_raw(arr: ClusterArrays, term_matches: jax.Array) -> jax.Array:
    """[P, N] summed weights of matching preferred node-affinity terms
    (nodeaffinity/node_affinity.go — Score).  One [P, S] @ [S, N] matmul in
    f32, STORED on the bf16 lattice (ops/bitplane.py — the oracle and
    native mirrors round identically); consumers upcast to f32 before
    reducing."""
    P, _ = arr.pod_pref_terms.shape
    S = term_matches.shape[0]
    ids = jnp.maximum(arr.pod_pref_terms, 0)
    w = jnp.where(arr.pod_pref_terms >= 0, arr.pod_pref_weights, 0.0)
    W = jnp.zeros((P, S), dtype=jnp.float32)
    W = W.at[jnp.arange(P)[:, None], ids].add(w)
    return bitplane.quantize_scores(W @ term_matches.astype(jnp.float32))


def _image_on(arr: ClusterArrays, cfg: ScoreConfig, image_sharded) -> bool:
    """Whether the ImageLocality stage has a real [P, N] matrix.  Under
    shard_map the local-shape heuristic (shape[1] == arr.N) is ambiguous when
    the local node count collapses to the replicated matrix's width of 1, so
    sharded callers resolve the check at GLOBAL shape and pass the verdict in
    as `image_sharded`."""
    if not cfg.enable_image:
        return False
    if image_sharded is not None:
        return bool(image_sharded)
    return arr.image_score.shape[1] == arr.N


def schedule_scan(
    arr: ClusterArrays, cfg: ScoreConfig, axis_name: Optional[str] = None,
    image_sharded: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The full scheduling step.  `arr` holds the whole cluster when
    axis_name is None, or this shard's node slice under shard_map.

    Returns (assignment i32[P] — GLOBAL node index or -1, node_used i32[N,R])."""
    TRACE_COUNTS["sharded_plain" if axis_name else "plain"] += 1
    local_n = arr.N
    if axis_name:
        base = lax.axis_index(axis_name).astype(jnp.int32) * local_n
    else:
        base = jnp.int32(0)
    my_nodes = base + jnp.arange(local_n, dtype=jnp.int32)

    with _subphase("hoist"):
        tm = filters.term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)  # [S, Nl]
        nodesel = filters.node_selection_ok_from(tm, arr)  # [P, Nl]
        pin = arr.pod_nodename[:, None]
        nodename_ok = jnp.where(pin == -1, True, pin == my_nodes[None, :])
        sf = (
            arr.node_valid[None, :]
            & arr.pod_valid[:, None]
            & filters.taints_ok(arr)
            & nodesel
            & nodename_ok
        )
    n_alloc = arr.node_alloc
    # static per-term node->domain map + key presence, hoisted out of the scan
    # (ops/pairwise.py module docstring: per-node state layout).  D is a
    # static Python int — domain id D means "node lacks the key".
    D = arr.term_counts0.shape[1] - 1
    dom_by_term = arr.node_dom[arr.term_key]  # i32[T, Nl]
    has_key_all = dom_by_term < D  # bool[T, Nl]

    # Scan inputs assembled conditionally: disabled stages (cfg.enable_*) never
    # materialize their [P, N] matrices — a constant-per-pod score term cannot
    # change argmax, so pruning is decision-preserving.
    xs = {"req": arr.pod_req, "sf": sf, "valid": arr.pod_valid}
    if cfg.enable_taint_score:
        with _subphase("hoist"):
            xs["pref"] = taint_prefer_counts(arr)  # [P, Nl]
    if cfg.enable_node_pref:
        with _subphase("hoist"):
            xs["na"] = _preferred_node_affinity_raw(arr, tm)  # [P, Nl]
    if cfg.enable_pairwise:
        xs.update(
            nodesel=nodesel,
            aff=arr.pod_aff_terms,
            anti=arr.pod_anti_terms,
            spread_t=arr.pod_spread_terms,
            spread_skew=arr.pod_spread_maxskew,
            spread_hard=arr.pod_spread_hard,
            mt=arr.pod_match_terms,
            mv=arr.pod_match_vals,
            aself=arr.pod_aff_self,
        )
        if cfg.enable_interpod_score:
            xs["pref_t"] = arr.pod_pref_aff_terms
            xs["pref_w"] = arr.pod_pref_aff_w
    if cfg.enable_ports:
        xs["ports"] = arr.pod_ports
    if _image_on(arr, cfg, image_sharded):
        xs["img"] = arr.image_score

    def norm_reverse(counts, feasible):
        with _subphase("normalize"):
            # bf16-stored raw planes upcast before the reduction (f32
            # accumulation rule); f32 inputs pass through untouched
            counts = counts.astype(jnp.float32)
            mx = _rmax(jnp.where(feasible, counts, 0.0), axis_name)
            return jnp.where(
                mx > 0, MAX_NODE_SCORE - MAX_NODE_SCORE * counts / mx,
                MAX_NODE_SCORE,
            )

    def step(state, xs):
        used, cnt_node, anti_node, pref_node, total_t, ports_used = state
        req, feas_row, valid = xs["req"], xs["sf"], xs["valid"]

        with _subphase("score"):
            feasible = feas_row & filters.fit_ok(req, used, n_alloc)
            if cfg.enable_ports:
                feasible &= pairwise.ports_ok(ports_used, xs["ports"])
            if cfg.enable_pairwise:
                spread_ok, spread_raw = pairwise.spread_step(
                    cnt_node, has_key_all, xs["spread_t"], xs["spread_skew"],
                    xs["spread_hard"], xs["nodesel"] & arr.node_valid,
                    axis_name,
                )
                feasible &= spread_ok & pairwise.interpod_required_ok(
                    cnt_node, anti_node, total_t, has_key_all, xs["aff"],
                    xs["anti"], xs["mt"], xs["mv"], xs["aself"],
                )
            requested = used + req[None, :]
            # score accumulation order mirrors the oracle exactly (float32
            # parity): fit(strategy), balanced, taint, nodeAffinity, spread
            total = cfg.fit_weight * fit_score(
                requested, n_alloc, cfg
            ) + cfg.balanced_weight * balanced_allocation(
                requested, n_alloc, cfg.score_resources
            )
            if cfg.enable_taint_score:
                total = total + cfg.taint_weight * norm_reverse(
                    xs["pref"], feasible
                )
            if cfg.enable_node_pref:
                with _subphase("normalize"):
                    # NodeAffinity preferred: DefaultNormalizeScore (not
                    # reversed); bf16-stored raw upcast first
                    na_row = xs["na"].astype(jnp.float32)
                    na_max = _rmax(jnp.where(feasible, na_row, 0.0), axis_name)
                    total = total + cfg.node_affinity_weight * jnp.where(
                        na_max > 0, na_row * MAX_NODE_SCORE / na_max, 0.0
                    )
            if cfg.enable_pairwise:
                total = total + cfg.spread_weight * norm_reverse(
                    spread_raw, feasible
                )
            if cfg.enable_pairwise and cfg.enable_interpod_score:
                # preferred inter-pod affinity: min/max normalization over
                # feasible (interpodaffinity/scoring.go — NormalizeScore)
                ip_raw = pairwise.interpod_pref_raw(
                    cnt_node, pref_node, has_key_all, xs["pref_t"],
                    xs["pref_w"], xs["mt"], xs["mv"],
                )
                with _subphase("normalize"):
                    mx = _rmax(jnp.where(feasible, ip_raw, -jnp.inf), axis_name)
                    mn = -_rmax(
                        jnp.where(feasible, -ip_raw, -jnp.inf), axis_name
                    )
                    ip_sc = jnp.where(
                        mx > mn, MAX_NODE_SCORE * (ip_raw - mn) / (mx - mn), 0.0
                    )
                total = total + cfg.interpod_weight * ip_sc
            if "img" in xs:  # ImageLocality: static, no per-pod normalization
                total = total + cfg.image_weight * xs["img"].astype(jnp.float32)
            total = jnp.where(feasible, total, -jnp.inf)
            best = _rmax(total, axis_name)
            schedulable = (best > -jnp.inf) & valid
            # lowest global index attaining the max
            cand = jnp.where((total == best) & feasible, my_nodes, _INT_MAX)
            choice = jnp.where(
                schedulable, _rmin(cand, axis_name).astype(jnp.int32), -1
            )

        with _subphase("commit"):
            return _step_commit(
                xs, used, cnt_node, anti_node, pref_node, total_t,
                ports_used, choice, req,
            )

    def _step_commit(xs, used, cnt_node, anti_node, pref_node, total_t,
                     ports_used, choice, req):
        placed = (my_nodes == choice)[:, None]
        used = used + placed.astype(used.dtype) * req[None, :]
        if cfg.enable_pairwise:
            # domain column of the chosen node, per term — owner shard broadcasts
            is_mine = (choice >= base) & (choice < base + local_n)
            local_col = jnp.clip(choice - base, 0, local_n - 1)
            dom_col = jnp.where(is_mine, dom_by_term[:, local_col], 0)
            if axis_name:
                dom_col = lax.psum(dom_col, axis_name)
            cnt_node, anti_node, total_t = pairwise.commit_counts(
                cnt_node, anti_node, total_t, dom_by_term, D,
                choice, dom_col, xs["mt"], xs["mv"], xs["anti"],
            )
            if cfg.enable_interpod_score:
                # the committed pod's own preferred terms join the symmetric
                # half for later pods
                bids = jnp.maximum(xs["pref_t"], 0)
                bw = jnp.where((xs["pref_t"] >= 0) & (choice >= 0), xs["pref_w"], 0.0)
                pref_node = pref_node.at[bids].add(
                    bw[:, None] * (dom_by_term[bids] == dom_col[bids][:, None])
                )
                if cfg.hard_pod_affinity_weight:
                    # ... and its REQUIRED affinity terms at hardPodAffinityWeight
                    # (interpodaffinity/scoring.go — processExistingPod)
                    aids = jnp.maximum(xs["aff"], 0)
                    aw = jnp.where(
                        (xs["aff"] >= 0) & (choice >= 0),
                        jnp.float32(cfg.hard_pod_affinity_weight),
                        0.0,
                    )
                    pref_node = pref_node.at[aids].add(
                        aw[:, None] * (dom_by_term[aids] == dom_col[aids][:, None])
                    )
        if cfg.enable_ports:
            ports_used = ports_used | (placed & xs["ports"][None, :])
        return (used, cnt_node, anti_node, pref_node, total_t, ports_used), choice

    # initial per-node state: ONE hoisted [T, N] gather each (cheap outside
    # the scan), bit-identical to reading the [T, D+1] tables per step
    with _subphase("hoist"):
        cnt_node0 = jnp.take_along_axis(arr.term_counts0, dom_by_term, axis=1)
        anti_node0 = jnp.take_along_axis(arr.anti_counts0, dom_by_term, axis=1)
        pref_node0 = jnp.take_along_axis(arr.pref_own0, dom_by_term, axis=1)
        total_t0 = arr.term_counts0[:, :D].sum(axis=1)
    state0 = (
        arr.node_used, cnt_node0, anti_node0, pref_node0, total_t0,
        arr.node_ports0,
    )
    (used_final, _, _, _, _, _), choices = lax.scan(step, state0, xs)
    return choices, used_final


# pods per chunk, PER KERNEL — the two chunked designs scale oppositely
# with C (round-5 sweep, BENCH_ROUNDS_PROOF_r05.json chunk_sweep):
#
#   rounds kernel: total rounds barely grow as C shrinks (config-3:
#   1400@128 -> 1710@16) while per-round re-hoist bytes scale ∝ C, so
#   SMALL chunks win big — 55.2 s @128 vs 8.6 s @16 at config-3 scale,
#   611 s vs 145 s at full north-star scale on the CPU sim, decisions
#   bit-identical throughout.  16 ships.
#   chunked (top-K) kernel: the hoist+top_k is amortized per chunk and
#   the O(C) while-carry is already tiny, so smaller chunks just add
#   outer scan steps — 22.1 s @128 vs 28.5 s @16 at north-star scale.
#   128 stays.
#
# KTPU_CHUNK / KTPU_RCHUNK override for sweeps (import-time, like
# KTPU_REPAIR_ITERS: fresh process per point).
_CHUNK = int(os.environ.get("KTPU_CHUNK", "128"))
_RCHUNK = int(os.environ.get("KTPU_RCHUNK", "16"))
# chunk size for the INCREMENTAL chunked path (ops/incremental.py).  The
# dense kernel wants big chunks because the [C, N, R] hoist and the [C, N]
# top-k amortize per chunk; the incremental path hoists per CYCLE and
# top-ks the [U, N] class matrix (independent of C), so only the
# O(C^2·K)-per-round loop costs scale with C and SMALL chunks win —
# measured on the CPU sim at 12.8k x 5k: dense@128 12.4 s, inc@128 8.4 s,
# inc@32 1.2 s, same decisions throughout (chunk size never changes
# decisions, only commit ordinals).  P (bucketed, pow2 >= _CHUNK) is
# always divisible by it.
_INC_CHUNK = tuning.tuned_knob("KTPU_INC_CHUNK", 32)
_SPECZ = 16  # usable list entries precomputed per pod for pass-1 speculation
_SPEC_ITERS = 4  # jump-to-first-unclaimed iterations (cross-group collisions)

# ---- class-batched commit waves (incremental route only) ----
# The wave stage (_wave_commit_stage) resolves MOST pods before the
# prefix-commit round loop ever runs: per EPOCH it top-k's the resident
# [U1, N] class matrix once, then commits pods in blocks of E via a
# certified stale-max interference check — O(P/E) block iterations of
# [U1, E]-scale work instead of O(P/C) chunks x O(C) rounds of
# O(C^2 K)-scale work.  Decisions stay bit-identical to the serial oracle:
# an uncertifiable pod triggers ONE exact dense [N, R] rescore (the
# "genuinely interfering class" fallback) and an epoch refresh, and
# whatever the block budget leaves uncommitted falls through to the
# unchanged round loop (stage B).  Knobs are trace-time constants resolved
# env > autotuned winner (ops/tuning.py, bench/autotune.py) > default:
#   KTPU_CLASS_WAVES  0 disables the wave stage (pure round-loop A/B leg)
#   KTPU_WAVE_K       per-class candidate list length per epoch (top-k K)
#   KTPU_WAVE_BLOCK   pods certified per block iteration (E)
#   KTPU_WAVE_ITERS   pointer-dispersal fixpoint iterations per block
#                     (verified exactly afterwards — more iterations only
#                     reduce benign truncations, never change decisions)
_CLASS_WAVES = os.environ.get("KTPU_CLASS_WAVES", "1") != "0"
# defaults from the north-star-scale sweep (50k x 20k, CPU sim): small
# blocks with a deep dispersal fixpoint beat wide blocks — certification
# truncates at the first unsettled pod, so past ~E=64 extra width only
# adds per-block cost.  KW=256 balances epoch lifetime (list exhaustion
# forces a [U1, N] top-k refresh) against per-block walk width; measured
# 86 refreshes over 1319 blocks at 50k x 20k.  bench/autotune.py persists
# per-box winners that override these (ops/tuning.py).
_WAVE_K = tuning.tuned_knob("KTPU_WAVE_K", 256)
_WAVE_BLOCK = tuning.tuned_knob("KTPU_WAVE_BLOCK", 48)
_WAVE_ITERS = tuning.tuned_knob("KTPU_WAVE_ITERS", 12)

# speculate->repair iterations per round (rounds kernel).  Swept in fresh
# processes at BASELINE config-3 scale, 10k x 5k warm steps on the CPU sim
# (BENCH_ROUNDS_PROOF_r05.json): 1 iter -> 1400 rounds / 56.0 s, 2 ->
# 1306 / 64.0 s, 3 -> 1254 / 129.3 s.  Extra iterations cut rounds ~7%
# but each adds a full [C, N] repair pass per round, and the pass cost
# dominates the round savings at every measured point — rounds/chunk is
# NOT a cost proxy.  1 is the measured optimum; decisions are identical
# at every setting (sweep_decisions_identical).  At north-star scale the
# round count is LOWER per chunk (8.7 vs 17.5 — 200-app term sharing is
# sparser), so the case for extra repair shrinks further.
# KTPU_REPAIR_ITERS overrides for tuning sweeps (read at import; the value
# is baked into each jit trace, so sweep points must run in fresh
# processes — bench/rounds_proof.py does).
_REPAIR_ITERS = int(os.environ.get("KTPU_REPAIR_ITERS", "1"))

# Trace-time counters, bumped when a kernel's Python body actually runs
# under jit tracing (once per cache entry).  Tests use them to prove WHICH
# kernel a routed call compiled — the routing env override is read at trace
# time, so asserting on the predicate alone can be vacuous against a warm
# jit cache.
TRACE_COUNTS = {
    "plain": 0, "chunked": 0, "rounds": 0,
    # mesh-sharded variants (parallel/sharded.py): bumped when the kernel
    # traces under shard_map, so tests/benches can prove a routed call
    # actually compiled the sharded program for its route
    "sharded_plain": 0, "sharded_chunked": 0, "sharded_rounds": 0,
    # incremental (equivalence-class / dirty-node, ops/incremental.py)
    # variants of the production kernels
    "chunked_inc": 0, "rounds_inc": 0,
    "sharded_chunked_inc": 0, "sharded_rounds_inc": 0,
    # class-batched commit-wave stage (_wave_commit_stage): bumped when the
    # incremental chunked kernel traces WITH the wave stage armed
    # (KTPU_CLASS_WAVES) — trace-guard tests prove the wave actually
    # compiled (or didn't, for the degenerate U == P dense route)
    "class_waves": 0,
}


def reset_trace_counts() -> None:
    """Zero TRACE_COUNTS — called at harness/bench run start so counters
    never bleed across runs in one process (back-to-back bench.harness
    invocations previously reported cumulative route_trace_counts)."""
    for k in TRACE_COUNTS:
        TRACE_COUNTS[k] = 0


def _chunkable(arr: ClusterArrays, cfg: ScoreConfig) -> bool:
    """The chunked scan applies when the ONLY scan-carried state is node
    usage: no pairwise/ports stages and no per-pod normalization stages
    (taint/nodeAffinity/image) — which is exactly the north-star
    heterogeneous shape and the basic/gang configs."""
    return (
        not cfg.enable_pairwise
        and not cfg.enable_ports
        and not cfg.enable_taint_score
        and not cfg.enable_node_pref
        and not (cfg.enable_image and arr.image_score.shape[1] == arr.N)
        and arr.P >= _CHUNK
        and arr.P % _CHUNK == 0
    )


def _chunk_routed(arr: ClusterArrays, cfg: ScoreConfig) -> bool:
    """Routing decision: chunked only off-CPU.  The rounds design trades
    per-step count for wider vectorized round bodies, which wins on TPU
    (scan loop overhead ~3us/step) but loses to the plain scan on the CPU
    interpreter.  Decisions are bit-identical on both paths
    (tests/test_assign_parity.py), so this is a pure performance choice
    evaluated at trace time.

    KTPU_FORCE_CHUNKED=1 forces the chunked routing on any backend (so the
    CPU sim can soak the production route end-to-end — round-3 verdict);
    =0 forces the plain scan.  Read at TRACE time: changing it after a
    shape/cfg has been jit-cached has no effect on that cache entry."""
    ov = os.environ.get("KTPU_FORCE_CHUNKED", "")
    if ov == "1":
        return _chunkable(arr, cfg)
    if ov == "0":
        return False
    return jax.default_backend() != "cpu" and _chunkable(arr, cfg)


def _wave_commit_stage(
    cls, pvalid, preq, used_init, t0u_init, stat_full, n_alloc_full,
    req_u, score_flat, nl=None,
):
    """CLASS-BATCHED COMMIT WAVES — the stage that collapses the O(C^2 K)
    prefix-commit round loop (ISSUE 17 / ROADMAP-1).  Commits pods in
    BLOCKS of E at the frontier, certifying each commit against an EXACT
    stale-max interference check instead of re-speculating per round.

    EPOCH STRUCTURE.  An epoch starts by top-k'ing the resident (and
    continuously-patched) [U1, N] class matrix into per-class candidate
    lists (tv, ti)[U1, KW] — `lax.top_k` keeps equal values in ascending
    node order, the deterministic selectHost tie-break.  Within an epoch,
    every certified commit goes to a node no other commit of the epoch has
    touched (`claimed`), so each touched node's POST-placement score column
    s2[U1] is computed exactly once and never superseded — which makes a
    running lexicographic (max value, min node) register (bmax, bnode)[U1]
    over those columns an EXACT summary of every touched node, per class.

    CERTIFICATION.  A pod's speculative pick is the first feasible
    unclaimed entry of its class list (pointer walk below).  That entry
    dominates every UNtouched node: untouched nodes keep their epoch-start
    scores (usage only grows at touched nodes), in-list entries are sorted
    with lowest-index ties, and out-of-list nodes score <= the last list
    entry with a higher index than any equal-valued in-list node.  So the
    pod's true argmax is either its pick or the best touched node — and
    the latter is exactly (bmax, bnode) extended with the in-block earlier
    picks' s2 columns via an exclusive associative scan.  The pick is
    CERTIFIED when it wins that lexicographic comparison; a -1 (unschedul-
    able) outcome is certified when the class list was not truncated
    (nf < KW: every epoch-start-feasible node is IN the list), every
    usable entry is claimed by an earlier pod, and no touched node is
    feasible (ex_v == -inf).  Fit monotonicity (usage only grows) keeps
    epoch-start infeasibility valid all epoch.

    POINTER WALK.  Same-class pods in a block share identical lists, so
    they are seeded with successive usable entries (rank within the
    class), then _WAVE_ITERS jump-to-first-unclaimed iterations settle
    cross-class collision chains — and an exact VERIFY pass (the pod owns
    its node, every earlier usable entry is claimed by an earlier pod)
    demotes any unsettled pod to uncertified, so the iteration count can
    never change decisions, only the benign truncation rate.

    FALLBACK.  The first uncertified pod q of a block is resolved by ONE
    exact dense [N, R] rescore under the prefix-committed usage — the
    "genuinely interfering class" per-pod fallback, bit-identical to the
    sequential scan's step for that pod (it handles same-node stacking by
    construction) — and the epoch ends (refresh next block).  Every block
    therefore commits >= 1 pod (its full prefix, or the fallback pod), the
    committed set is always a contiguous PREFIX of the batch, and the loop
    terminates; a static block budget caps the worst case, handing any
    remainder to the unchanged round loop (stage B) which continues the
    serial order exactly.

    The resident t0u matrix is patched at every committed column (prefix
    columns from their s2 snapshots, the fallback column by one [U1, R]
    recompute), so it stays bit-identical to a fresh class hoist against
    the running usage throughout — the cross-chunk dirty-list carry.

    PACKED PLANES (ops/bitplane.py — KTPU_PACK_MASKS): `stat_full` arrives
    as uint32 bit-plane words packed in per-shard-local blocks of `nl` bits
    (the tiled-all_gather layout; nl = N unsharded), tested per candidate
    column with bitplane.test_cols; the epoch `claimed` register is a
    single-block packed [ceil(N/32)] word vector (wave-internal — never
    gathered), OR-scattered at the O(E) commit frontier.  Same bits, 8x
    fewer resident/carried bytes.

    Returns (committed bool[P], out i32[P], ordinal i32[P] — the block
    index, a device-sweep ordinal like the round loop's round index,
    used i32[N, R], t0u f32[U1, N], n_blocks i32)."""
    P = cls.shape[0]
    U1, N = t0u_init.shape
    R = preq.shape[1]
    if nl is None:
        nl = N
    PM = bitplane.PACK_MASKS

    def st_cols(ids):
        """stat_full at candidate columns (GLOBAL ids) — [U1, *ids.shape]."""
        return bitplane.test_cols(stat_full, ids, nl) if PM else (
            stat_full[:, ids]
        )

    def cl_test(cl, ids):
        return bitplane.test_cols(cl, ids, N) if PM else cl[ids]

    def cl_set(cl, ids, on):
        if PM:
            return bitplane.set_cols(cl, ids, on, N)
        return cl.at[jnp.where(on, ids, N)].set(True, mode="drop")
    E = min(_WAVE_BLOCK, P)
    KW = min(_WAVE_K, N)
    # >= 1 pod commits per block, so P blocks always suffice; the budget
    # bounds pathological truncation storms (every block falling back at
    # q=0) — anything left over is stage B's, exactness never at stake
    max_blocks = (P // E + 1) * 8 + 32
    neg_inf = -jnp.inf
    idxE = jnp.arange(E, dtype=jnp.int32)
    ltE = idxE[None, :] < idxE[:, None]  # [i, j]: j < i
    kw_rng = jnp.arange(KW, dtype=jnp.int32)

    def refresh(t0u):
        tv, ti = lax.top_k(t0u, KW)  # [U1, KW] — ties to the lower index
        nf = (tv > neg_inf).sum(axis=1).astype(jnp.int32)
        return tv, ti, nf

    def body(st):
        (f, committed, out, ordn, used, t0u, claimed, bmax, bnode,
         tv, ti, nf, need_ep, epochs, blocks) = st
        # ---- (A) epoch refresh: new lists from the patched t0u; the
        # claimed set and the touched-node register restart empty ----
        tv, ti, nf = lax.cond(
            need_ep, refresh, lambda _: (tv, ti, nf), t0u
        )
        claimed = jnp.where(need_ep, jnp.zeros_like(claimed), claimed)
        bmax = jnp.where(need_ep, neg_inf, bmax)
        bnode = jnp.where(need_ep, _INT_MAX, bnode)
        # ---- (B) the block: E pods at the frontier (clamped at the tail;
        # re-covered pods are inactive and certify vacuously) ----
        start = jnp.minimum(f, P - E).astype(jnp.int32)
        bidx = start + idxE
        bcls = cls[bidx]  # [E]
        breq = preq[bidx]  # [E, R]
        bval = pvalid[bidx]
        active = ~committed[bidx]
        live = active & bval
        # ---- (C) pointer walk: first feasible unclaimed list entry ----
        tvb = tv[bcls]  # [E, KW]
        tib = ti[bcls]
        avail = (tvb > neg_inf) & ~cl_test(claimed, tib) & live[:, None]
        same = (bcls[:, None] == bcls[None, :]) & live[None, :]
        rank = (same & ltE).sum(axis=1).astype(jnp.int32)
        csum = jnp.cumsum(avail.astype(jnp.int32), axis=1)
        hit = csum == (rank + 1)[:, None]  # the (rank+1)-th usable entry
        pos = jnp.where(
            hit.any(axis=1), jnp.argmax(hit, axis=1).astype(jnp.int32), KW
        )

        def picked_nodes(pos):
            posc = jnp.minimum(pos, KW - 1)
            nd = jnp.take_along_axis(tib, posc[:, None], 1)[:, 0]
            return jnp.where(pos < KW, nd, N)  # N: sentinel (no pick)

        def claims(pos):
            nd = picked_nodes(pos)
            cm = jnp.full(N + 1, _INT_MAX, jnp.int32).at[nd].min(idxE)
            return nd, cm  # cm[n]: earliest block pod pointing at n

        for _ in range(_WAVE_ITERS):
            _, cm = claims(pos)
            elig = avail & ~(cm[tib] < idxE[:, None])
            pos = jnp.where(
                elig.any(axis=1),
                jnp.argmax(elig, axis=1).astype(jnp.int32), KW
            )
        nd, cm = claims(pos)
        # exact settlement check — unsettled pods fall back, so the
        # iteration count above is a pure perf knob
        own_ok = cm[nd] == idxE
        earlier_cl = cm[tib] < idxE[:, None]
        before = kw_rng[None, :] < pos[:, None]  # pos == KW: all entries
        prefix_ok = jnp.all(~(avail & before) | earlier_cl, axis=1)
        posc = jnp.minimum(pos, KW - 1)
        a_val = jnp.where(
            pos < KW, jnp.take_along_axis(tvb, posc[:, None], 1)[:, 0],
            neg_inf,
        )
        a_node = nd
        picked = live & (pos < KW)
        # ---- (D) post-placement snapshot columns s2[U1, E]: every class'
        # exact masked score at each picked node AFTER its pod lands —
        # the value a fresh hoist would compute there, and the exact
        # interference evidence for later pods ----
        an = jnp.minimum(a_node, N - 1)
        nu = used[an] + breq  # [E, R]
        na = n_alloc_full[an]
        free = na - nu
        fit2 = jnp.all(
            (req_u[:, None, :] == 0) | (req_u[:, None, :] <= free[None]),
            axis=2,
        )  # [U1, E] — same subtraction form as filters.fit_ok
        reqd2 = nu[None] + req_u[:, None, :]  # [U1, E, R]
        v2 = score_flat(
            reqd2.reshape(-1, R),
            jnp.broadcast_to(na[None], reqd2.shape).reshape(-1, R),
        ).reshape(U1, E)
        s2 = jnp.where(
            st_cols(an) & fit2 & picked[None, :], v2, neg_inf
        )
        s2n = jnp.where(picked, a_node, _INT_MAX)
        # ---- (E) exclusive lexicographic scan: best touched node each pod
        # sees = epoch register (bmax, bnode) + earlier in-block columns --
        v_ext = jnp.concatenate([bmax[:, None], s2], axis=1)  # [U1, E+1]
        n_ext = jnp.concatenate(
            [bnode[:, None], jnp.broadcast_to(s2n[None], (U1, E))], axis=1
        )

        def lexmax(a, b):
            av, an_ = a
            bv, bn = b
            tb = (bv > av) | ((bv == av) & (bn < an_))
            return jnp.where(tb, bv, av), jnp.where(tb, bn, an_)

        sv, sn = lax.associative_scan(lexmax, (v_ext, n_ext), axis=1)
        ex_v = sv[bcls, idxE]  # [E] — exclusive: col b covers base + <b
        ex_n = sn[bcls, idxE]
        # ---- (F) certification ----
        covered = (nf[bcls] < KW) if KW < N else jnp.full(E, True)
        cert_pick = (
            picked & own_ok & prefix_ok
            & ((a_val > ex_v) | ((a_val == ex_v) & (a_node < ex_n)))
        )
        cert_neg = (
            live & (pos >= KW) & prefix_ok & covered & (ex_v == neg_inf)
        )
        cert = ~live | cert_pick | cert_neg  # invalid pods: -1, certified
        ncert = active & ~cert
        q = jnp.where(ncert.any(), jnp.argmax(ncert), E).astype(jnp.int32)
        inpre = idxE < q
        commit_b = active & inpre
        place_b = commit_b & cert_pick
        ucol = jnp.where(place_b, a_node, N)
        used2 = used.at[ucol].add(
            jnp.where(place_b[:, None], breq, 0), mode="drop"
        )
        # ---- (G) per-pod fallback: one exact dense rescore for the first
        # uncertified pod, under the prefix-committed usage ----
        do_fb = q < E
        qc = jnp.minimum(q, E - 1)
        fcls = bcls[qc]
        freq = breq[qc]

        def fb_rescore(args):
            used2, freq, fstat = args
            if PM:  # packed class row -> dense [N] at this narrow frontier
                fstat = bitplane.unpack_blocks(fstat, nl)
            ffit = filters.fit_ok(freq, used2, n_alloc_full)  # [N]
            fvals = jnp.where(
                fstat & ffit,
                score_flat(used2 + freq[None], n_alloc_full),
                neg_inf,
            )
            return jnp.where(
                fvals.max() > neg_inf, jnp.argmax(fvals), -1
            ).astype(jnp.int32)

        # the [N, R] rescore only runs when the block actually truncated
        # (cond false-branch = the skip, matching the stage-B convention:
        # the analytic ledger charges the branch that runs on the collapsed
        # fast path)
        t_fb = lax.cond(
            do_fb, fb_rescore, lambda _: jnp.int32(-1),
            (used2, freq, stat_full[fcls]),
        )
        fb_ok = do_fb & (t_fb >= 0)
        fcol = jnp.where(fb_ok, t_fb, N)
        used3 = used2.at[fcol].add(jnp.where(fb_ok, freq, 0), mode="drop")
        # ---- (H) absorb: outputs, claims, register fold, t0u patch ----
        scat = jnp.where(commit_b, bidx, P)
        out = out.at[scat].set(
            jnp.where(place_b, a_node, -1), mode="drop"
        )
        committed = committed.at[scat].set(True, mode="drop")
        ordn = ordn.at[scat].set(blocks, mode="drop")
        fscat = jnp.where(do_fb, start + q, P)
        out = out.at[fscat].set(t_fb, mode="drop")
        committed = committed.at[fscat].set(True, mode="drop")
        ordn = ordn.at[fscat].set(blocks, mode="drop")
        claimed = cl_set(claimed, ucol, place_b)
        # a fallback STACKS when its exact argmax is a node this epoch
        # already touched (the prefix claims are already folded in above)
        # — the one case that breaks the touched-once-per-epoch invariant
        # and forces a refresh.  An untouched fallback node just becomes
        # one more touched node: claim it, fold its post-placement column,
        # and the epoch continues
        fnc = jnp.minimum(fcol, N - 1)
        stacked = fb_ok & cl_test(claimed, fnc)
        claimed = cl_set(claimed, fcol, fb_ok)
        # fold the committed prefix's columns into the epoch register
        cv = jnp.where(inpre[None], s2, neg_inf)
        cn = jnp.where(inpre, s2n, _INT_MAX)
        m = cv.max(axis=1)
        mn = jnp.where(cv == m[:, None], cn[None], _INT_MAX).min(axis=1)
        tb = (m > bmax) | ((m == bmax) & (mn < bnode))
        bmax = jnp.where(tb, m, bmax)
        bnode = jnp.where(tb, mn, bnode)
        # patch committed columns: prefix picks from their s2 snapshots
        # (each touched once this epoch — exact), then the fallback column
        # by one [U1, R] recompute against the post-fallback usage (it may
        # STACK on a prefix node; last write wins with the exact value)
        t0u = t0u.at[:, ucol].set(s2, mode="drop")
        fnu = used3[fnc]
        fna = n_alloc_full[fnc]
        ffit_u = jnp.all(
            (req_u == 0) | (req_u <= (fna - fnu)[None]), axis=1
        )  # [U1]
        fv_u = score_flat(
            fnu[None] + req_u, jnp.broadcast_to(fna[None], req_u.shape)
        )
        fcv = jnp.where(st_cols(fnc) & ffit_u, fv_u, neg_inf)
        t0u = t0u.at[:, fcol].set(fcv, mode="drop")
        # fold the fallback's post-placement column too (dead on refresh)
        fv2 = jnp.where(fb_ok, fcv, neg_inf)
        fn2 = jnp.where(fb_ok, t_fb, _INT_MAX)
        t2 = (fv2 > bmax) | ((fv2 == bmax) & (fn2 < bnode))
        bmax = jnp.where(t2, fv2, bmax)
        bnode = jnp.where(t2, fn2, bnode)
        f = jnp.where(q == E, start + E, start + q + 1).astype(jnp.int32)
        # refresh on stacking (exactness demands it) or on a starved block
        # (the epoch lists are spent — new top-k beats grinding fallbacks)
        need_ep = do_fb & (stacked | (q < E // 8))
        return (f, committed, out, ordn, used3, t0u, claimed, bmax, bnode,
                tv, ti, nf, need_ep, epochs + need_ep.astype(jnp.int32),
                blocks + 1)

    st0 = (
        jnp.int32(0),
        jnp.zeros(P, dtype=jnp.bool_),
        jnp.full(P, -1, dtype=jnp.int32),
        jnp.zeros(P, dtype=jnp.int32),
        used_init,
        t0u_init,
        jnp.zeros(bitplane.words_for(N), dtype=jnp.uint32)
        if PM else jnp.zeros(N, dtype=jnp.bool_),
        jnp.full(U1, neg_inf, dtype=t0u_init.dtype),
        jnp.full(U1, _INT_MAX, dtype=jnp.int32),
        jnp.zeros((U1, KW), dtype=t0u_init.dtype),
        jnp.zeros((U1, KW), dtype=jnp.int32),
        jnp.zeros(U1, dtype=jnp.int32),
        jnp.bool_(True),
        jnp.int32(0),
        jnp.int32(0),
    )
    st = lax.while_loop(
        lambda s: (s[0] < P) & (s[-1] < max_blocks), body, st0
    )
    _, committed, out, ordn, used, t0u = st[:6]
    return committed, out, ordn, used, t0u, st[-1], st[-2]


def schedule_scan_chunked(
    arr: ClusterArrays, cfg: ScoreConfig, with_rounds: bool = False,
    with_ordinals: bool = False, axis_name: Optional[str] = None,
    axis_size: int = 1, image_sharded: Optional[bool] = None, inc=None,
):
    """Chunked sequential-commit scan via PREFIX-COMMIT SPECULATION rounds,
    BIT-IDENTICAL to schedule_scan for fit+balanced-only configs
    (tests/test_assign_parity.py — chunked cases).

    INCREMENTAL MODE (`inc` = ops/incremental.py — IncState): the [C, Nl]
    per-chunk dense hoist is replaced by a CLASS hoist [U1, N] (U1 = unique
    specs + padding class, U1 ≪ P for template-stamped waves) that arrives
    precomputed vs cycle-start usage (resident across warm cycles,
    dirty-column patched by the HoistCache), is carried through the chunk
    scan, and is PATCHED at committed node columns against the new usage —
    the same O(C)-column patching discipline schedule_scan_rounds applies,
    lifted to the chunk level.  Per-pod score rows are gathers of their
    class row (rows within a class are bit-identical by construction,
    api/delta.py — _pod_side), and lax.top_k over identical rows is
    deterministic, so decisions are bit-identical to the dense path
    (tests/test_incremental.py).  Per-chunk hoist FLOPs drop from
    O(C·N·R) to O(U1·C·R) patching; the only O(N) per-chunk work left is
    the class top-k ([U1, N] when U1 <= C, else the gathered [C, N]).

    The per-pod scan's latency floor is the sequential step count: ~3us of
    on-device loop overhead per `lax.scan` step x 50k pods =~ the whole
    budget, regardless of per-step width (measured on v5e).  This path
    replaces the per-pod loop with a small number of vectorized ROUNDS:

      - each CHUNK of C pods hoists dense scores [C, N] against chunk-start
        usage once (MXU/VPU-batched) and keeps the top K=C+1 candidates per
        pod (`lax.top_k`: values desc, ties to the lower index — the same
        tie-break as the deterministic selectHost mode);
      - a `lax.while_loop` of rounds then (1) SPECULATES a choice for every
        uncommitted pod, (2) REVALIDATES each choice exactly under the
        cumulative intra-round usage of earlier pods' picks, and (3) commits
        the longest prefix whose revalidated choice is unchanged.  The first
        uncommitted pod is always exact, so every round commits >= 1 pod.

    Speculation (pass 1) exploits the plateau structure of the score
    landscape: one placement generically drops a node off its tied-score
    plateau, so pods sharing a plateau head are seeded with SUCCESSIVE
    usable list entries (rank within same-head group), then a few
    fixed-point iterations advance pointers past cross-group collisions.
    A wrong guess only shortens the committed prefix — validation (pass 2)
    recomputes the true argmax from exactly-rescored candidates.

    Validation candidates per pod i: (a) chunk-dirty nodes (committed in
    previous rounds; <= C of them, tracked in `dlist` with their live usage
    in `dsu`), rescored with the same float32 formulas as the hoist;
    (b) nodes picked intra-round by pods j < i, rescored under round-start
    usage plus an exclusive int32 prefix sum of earlier picks' requests
    (same adds, same order as the sequential scan — exact); (c) the first
    top-K entry that is neither dirty nor intra-round-picked — which
    dominates every untouched node on both score and the lowest-index
    tie-break, because top_k keeps the lowest-indexed ties and anything
    outside the list scores <= the last list entry.  Fit is monotone (usage
    only grows), so a -inf hoisted entry stays infeasible and static
    feasibility can be read off total0.

    The while-loop carry is deliberately O(C)-sized (slot usage, clean-list
    flags) — carrying [N]-shaped state through a while_loop costs ~65us per
    iteration on v5e regardless of the body.  Node usage [N, R] is updated
    once per chunk from the committed choices.  Exact because fit/least/
    balanced depend on per-node usage only — there are no cross-node
    normalizations on this path.

    SHARDED EXECUTION (axis_name set, parallel/sharded.py): the node axis of
    every [*, N] input is a shard_map slice.  The expensive parts — the
    [P, Nl] static-feasibility masks and the per-chunk [C, Nl, R] hoist —
    stay shard-local; ONE all_gather per chunk stitches the masked [C, N]
    score matrix (elementwise math on a node slice is bit-identical to the
    same columns of the dense hoist, so the gathered matrix IS the
    single-device total0), and the prefix-commit round loop then runs
    REPLICATED on it: literally the single-device code on identical inputs,
    so decisions are bit-identical by construction.  The [N, R] usage/alloc
    arrays are all-gathered once and carried replicated (they are ~1000x
    smaller than the masks; the candidate-column alternative would gather
    [C, C*K] ≈ the same bytes as [C, N] with far more collectives).  The
    loop's per-round cost is O(C^2), independent of N — only the hoist
    scales with the node axis, and the hoist is what shards."""
    use_inc = inc is not None
    TRACE_COUNTS[
        ("sharded_chunked" if axis_name else "chunked")
        + ("_inc" if use_inc else "")
    ] += 1
    local_n = arr.N
    if axis_name:
        base = lax.axis_index(axis_name).astype(jnp.int32) * local_n
        N = local_n * axis_size
        n_alloc_full = lax.all_gather(
            arr.node_alloc, axis_name, axis=0, tiled=True
        )
        used_init = lax.all_gather(arr.node_used, axis_name, axis=0, tiled=True)
    else:
        base = jnp.int32(0)
        N = local_n
        n_alloc_full = arr.node_alloc
        used_init = arr.node_used
    my_nodes = base + jnp.arange(local_n, dtype=jnp.int32)

    P, R = arr.P, arr.R
    C = _INC_CHUNK if use_inc else _CHUNK
    K = min(C + 1, N)  # K == N: the list is exhaustive, guarded by .any()
    Z = min(_SPECZ, K)  # usable entries precomputed for pass-1 speculation
    res = cfg.score_resources
    neg_inf = -jnp.inf
    idxC = jnp.arange(C, dtype=jnp.int32)
    jlt = idxC[None, :] < idxC[:, None]  # [i, j]: j < i

    reqs = arr.pod_req.reshape(P // C, C, R)
    valids = arr.pod_valid.reshape(P // C, C)
    if use_inc:
        # the static-feasibility and base-score hoists arrive precomputed
        # per CLASS (resident across cycles); the [P, Nl] sf prelude and
        # per-chunk dense hoist below never trace
        U1 = inc.req_u.shape[0]
        req_u = inc.req_u
        with _subphase("hoist"):
            # packed planes: stat & fit is the same bitwise AND on uint32
            # words as on dense bools; the [U1, Nl] dense view exists only
            # at this t0u frontier (scores are dense f32 regardless)
            sfw = inc.stat_u & inc.fit_u
            t0u_init = jnp.where(
                bitplane.unpack(sfw, local_n)
                if bitplane.PACK_MASKS else sfw,
                inc.base_u, neg_inf,
            )
            if axis_name:
                # stitch the shard-local class hoists once per cycle; the
                # chunk scan then carries the full [U1, N] matrix replicated
                # (the non-inc path gathers [C, N] per chunk — this is
                # strictly less collective traffic whenever U1 < C *
                # n_chunks)
                t0u_init = lax.all_gather(
                    t0u_init, axis_name, axis=1, tiled=True
                )
                stat_full = lax.all_gather(
                    inc.stat_u, axis_name, axis=1, tiled=True
                )
            else:
                stat_full = inc.stat_u
        clss = inc.cls.reshape(P // C, C)
        sfs = None
    else:
        with _subphase("hoist"):
            tm = filters.term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)
            if bitplane.PACK_MASKS:
                # chunk-wise packed hoist: each C-row block computes its
                # dense [C, Nl] mask and packs it immediately (lax.map =
                # sequential blocks), so the widest mask transient is
                # [C, Nl] — the resident plane is [P, Wl] uint32 words, the
                # 8x pn_masks cut shard_hbm_estimate prices
                pod_blocks = (
                    arr.pod_terms.reshape(P // C, C, -1),
                    arr.pod_has_sel.reshape(P // C, C),
                    arr.pod_tol_ns.reshape(P // C, C, -1),
                    arr.pod_nodename.reshape(P // C, C),
                    arr.pod_valid.reshape(P // C, C),
                )

                def _sf_block(px):
                    pt, ph, ptol, pnn, pv = px
                    sfb, _ = filters.static_feasible_rows(
                        tm, arr.node_valid, arr.node_taint_ns, my_nodes,
                        pt, ph, ptol, pnn, pv,
                    )
                    return bitplane.pack(sfb)

                sfs = lax.map(_sf_block, pod_blocks)  # [P//C, C, Wl]
            else:
                nodesel = filters.node_selection_ok_from(tm, arr)
                pin = arr.pod_nodename[:, None]
                nodename_ok = jnp.where(
                    pin == -1, True, pin == my_nodes[None, :]
                )
                sf = (
                    arr.node_valid[None, :]
                    & arr.pod_valid[:, None]
                    & filters.taints_ok(arr)
                    & nodesel
                    & nodename_ok
                )
                sfs = sf.reshape(P // C, C, local_n)
        n_alloc = arr.node_alloc  # LOCAL node slice — hoist-side only

    def score_flat(requested, alloc):
        """Same formulas as the dense hoist, on flattened [*, R] rows —
        elementwise ops, so float32 results are bit-identical."""
        return cfg.fit_weight * fit_score(
            requested, alloc, cfg
        ) + cfg.balanced_weight * balanced_allocation(requested, alloc, res)

    def best_and_cand(vals, nodes, vu, iu):
        """Max score + lowest-node-index tie-break over per-pod candidate
        rows [C, D] plus the clean list head (vu, iu) per pod."""
        bd = vals.max(axis=1)
        best = jnp.maximum(bd, vu)
        cd = jnp.where(vals == best[:, None], nodes, _INT_MAX).min(axis=1)
        cand = jnp.minimum(cd, jnp.where(vu == best, iu, _INT_MAX))
        return best, cand

    # ---- class-batched commit waves (stage A) ----
    # The wave resolves a contiguous PREFIX of the batch (usually all of
    # it) before any chunk traces; the round loop below becomes stage B,
    # continuing the serial order over whatever the block budget left.
    # Runs on the replicated post-gather inputs, so it adds ZERO
    # collectives under sharding — the per-shard collective sequence is
    # KTPU009-identical to the wave-off trace.
    wave = use_inc and _CLASS_WAVES
    if wave:
        TRACE_COUNTS["class_waves"] += 1
        with _subphase("commit_batch"):
            wcom, wout, wordn, used_wave, t0u_wave, n_blocks, _n_ep = (
                _wave_commit_stage(
                    inc.cls, arr.pod_valid, arr.pod_req, used_init,
                    t0u_init, stat_full, n_alloc_full, req_u, score_flat,
                    nl=local_n,
                )
            )
        wcom_c = wcom.reshape(P // C, C)
        wout_c = wout.reshape(P // C, C)

    def chunk(carry, xs):
        if use_inc:
            used0, t0u = carry  # t0u: masked class scores vs current used0
            if wave:
                # wave-committed pods enter the round loop pre-committed
                # with their decisions in place; a fully-covered chunk's
                # while_loop runs zero rounds
                creq, ccls, cvalid, wcom0, wout0 = xs
            else:
                creq, ccls, cvalid = xs
            # per-pod scores are gathers of the pod's CLASS row — identical
            # rows, and lax.top_k on identical rows is deterministic, so
            # topv/topi match the dense path bit-for-bit.  Trace-time
            # choice: top-k the [U1, N] class matrix and gather [C, K]
            # lists when that is the smaller problem, else gather the
            # [C, N] rows first (a memory move, no score FLOPs either way)
            with _subphase("score"):
                if U1 <= C:
                    tv_u, ti_u = lax.top_k(t0u, K)
                    topv, topi = tv_u[ccls], ti_u[ccls]
                else:
                    topv, topi = lax.top_k(t0u[ccls], K)
                # per-pod validity (stat_u deliberately excludes pod_valid so
                # the resident state survives gang revocations): an invalid
                # pod's list empties exactly as the dense path's all--inf row
                # would, and every choice below is additionally cvalid-gated
                topv = jnp.where(cvalid[:, None], topv, neg_inf)
                t0u_T = t0u.T  # [N, U1] — contiguous row gathers below

            def stat_at(node_ids):
                # hoisted-entry feasibility at candidate columns, per pod:
                # class rows gathered through ccls (== total0_T[ids].T)
                return (t0u_T[node_ids] > neg_inf)[:, ccls].T  # [C, D]
        else:
            used0 = carry  # FULL [N, R] usage (replicated under sharding)
            creq, csf, cvalid = xs
            if bitplane.PACK_MASKS:
                # the per-chunk unpack frontier: [C, Wl] words -> [C, Nl]
                csf = bitplane.unpack(csf, local_n)
            if axis_name:
                used0_l = lax.dynamic_slice_in_dim(
                    used0, base, local_n, axis=0
                )
            else:
                used0_l = used0
            # hoisted dense scores vs chunk-start usage (vmap = the per-step
            # ops batched, so float32 results are bit-identical to the plain
            # scan); shard-local: [C, Nl, R] intermediates, this kernel's
            # biggest block
            with _subphase("hoist"):
                requested = used0_l[None, :, :] + creq[:, None, :]  # [C,Nl,R]
                fit0 = jax.vmap(filters.fit_ok, (0, None, None))(
                    creq, used0_l, n_alloc
                )
                total0 = cfg.fit_weight * jax.vmap(
                    lambda rq, al: fit_score(rq, al, cfg), (0, None)
                )(requested, n_alloc) + cfg.balanced_weight * jax.vmap(
                    balanced_allocation, (0, None, None)
                )(requested, n_alloc, res)
                total0 = jnp.where(csf & fit0, total0, neg_inf)  # [C, Nl]
                if axis_name:
                    # stitch the shard-local hoists into the full masked
                    # score matrix; from here the round loop is replicated
                    # verbatim
                    total0 = lax.all_gather(
                        total0, axis_name, axis=1, tiled=True
                    )
            with _subphase("score"):
                topv, topi = lax.top_k(total0, K)  # [C, K] each
                # row-major transpose: [C, D] static-feasibility lookups
                # below become contiguous row gathers instead of strided
                # column gathers
                total0_T = total0.T  # [N, C]

            def stat_at(node_ids):
                return total0_T[node_ids].T > neg_inf  # [C, D]
        req_b = creq[:, None, :]  # [C(pod), 1, R]

        def rescore(node_ids, node_usage):
            """Exact scores of every pod [C] at nodes node_ids [D] under
            node_usage [D, R]: (fit bool[C, D], value f32[C, D], static
            feasibility bool[C, D])."""
            da = n_alloc_full[node_ids]  # [D, R]
            fit = jax.vmap(filters.fit_ok, (0, None, None))(
                creq, node_usage, da
            )  # [C, D]
            reqd = node_usage[None] + req_b  # [C, D, R]
            shape = reqd.shape
            vals = score_flat(
                reqd.reshape(-1, R),
                jnp.broadcast_to(da[None], shape).reshape(-1, R),
            ).reshape(shape[0], shape[1])
            static = stat_at(node_ids)  # [C, D]
            return fit, vals, static

        def round_body(st):
            committed, out, ord_, cleank, dlist, dsu, nd, nrounds = st
            unc = ~committed
            # ---- pass 1: speculative choices vs live usage ----
            with _subphase("speculate"):
                dn = jnp.maximum(dlist, 0)
                dvalid = dlist >= 0
                dfit, dvals, dstat = rescore(dn, dsu)
                M2 = jnp.where(dvalid[None] & dstat & dfit, dvals, neg_inf)
                usablek = cleank & (topv > neg_inf)
                ukey = jnp.where(usablek, K - jnp.arange(K, dtype=jnp.int32), 0)
                _, upos = lax.top_k(ukey, Z)  # first Z usable positions
                uok = jnp.take_along_axis(ukey, upos, 1) > 0  # [C, Z]
                head = jnp.take_along_axis(topi, upos[:, :1], 1)[:, 0]  # [C]
                have0 = uok[:, 0]
                # seed: rank among earlier uncommitted pods with the same head
                # (same-spec pods share whole lists; they take successive
                # entries), then advance pointers past cross-group collisions
                same_head = (
                    (head[:, None] == head[None, :]) & have0[None, :] & unc[None, :]
                )
                ptr = jnp.minimum(
                    (same_head & jlt).sum(axis=1).astype(jnp.int32), Z - 1
                )
                # jump-to-first-unclaimed iterations: each pod claims its
                # pointed entry; pods whose entry is claimed by an earlier pod
                # jump to their first entry claimed by no earlier pod.  The
                # rank seed already disperses same-head (same-spec) groups, so
                # a couple of iterations settle cross-group collision chains.
                nodes_z = jnp.take_along_axis(topi, upos, 1)  # [C, Z]
                okr = jnp.take_along_axis(uok, ptr[:, None], 1)[:, 0] & unc
                for _ in range(_SPEC_ITERS):
                    claim = jnp.where(
                        okr,
                        jnp.take_along_axis(nodes_z, ptr[:, None], 1)[:, 0],
                        -1,
                    )
                    cb = (
                        (nodes_z[:, :, None] == claim[None, None, :])
                        & jlt[:, None, :]
                    ).any(axis=2)
                    free = uok & ~cb
                    has = free.any(axis=1)
                    ptr = jnp.where(has, jnp.argmax(free, axis=1), Z - 1)
                    okr = has & unc
                sel = jnp.take_along_axis(upos, ptr[:, None], 1)[:, 0]
                vu = jnp.where(
                    okr, jnp.take_along_axis(topv, sel[:, None], 1)[:, 0], neg_inf
                )
                iu = jnp.take_along_axis(topi, sel[:, None], 1)[:, 0]
                best1, cand1 = best_and_cand(
                    M2, jnp.broadcast_to(dn[None], (C, C)), vu, iu
                )
                c = jnp.where(
                    (best1 > neg_inf) & unc & cvalid, cand1.astype(jnp.int32), -1
                )
            # ---- pass 2: revalidate under intra-round prefix commits ----
            with _subphase("repair"):
                act = unc & (c >= 0)
                cn = jnp.maximum(c, 0)
                # cumulative usage each pod i sees at node c_j from pods k < i
                # (exclusive int32 prefix sum == the adds the per-pod scan
                # performs, in the same order — exact; log-depth associative
                # scan, jnp.cumsum lowers to O(C^2) reduce_window on TPU)
                E = (c[:, None] == c[None, :]) & act[:, None]  # [C(k), C(j)]
                T = E[:, :, None] * creq[:, None, :]  # [C, C, R]
                cum = lax.associative_scan(jnp.add, T, axis=0) - T
                # round-start usage at c_j: dirty nodes live in dsu, clean nodes
                # are untouched since chunk start
                eqd = (c[:, None] == dlist[None, :]) & dvalid[None, :]  # [C, C]
                hasslot = eqd.any(axis=1)
                sl = jnp.argmax(eqd, axis=1)
                cu = jnp.where(hasslot[:, None], dsu[sl], used0[cn])  # [C, R]
                ca = n_alloc_full[cn]
                cstat = stat_at(cn)  # [C, C]
                uij = cu[None] + cum  # [C, C, R]
                # fit of pod i at node c_j under its intra-round usage uij[i, j]
                fitij = jax.vmap(filters.fit_ok, (0, 0, None))(creq, uij, ca)
                reqij = uij + req_b
                shape = reqij.shape
                vij = score_flat(
                    reqij.reshape(-1, R),
                    jnp.broadcast_to(ca[None], shape).reshape(-1, R),
                ).reshape(C, C)
                Mij = jnp.where(act[None, :] & jlt & cstat & fitij, vij, neg_inf)
                # dirty nodes picked intra-round before i: superseded by Mij.
                # prefix-any over j < i as a [C,C]x[C,C] bool matmul (MXU)
                D2 = (dlist[None, :] == c[:, None]) & act[:, None] & dvalid[None, :]
                excl2 = (
                    jlt.astype(jnp.float32) @ D2.astype(jnp.float32)
                ) > 0.0  # [C(i), C(d)]
                M2x = jnp.where(excl2, neg_inf, M2)
                # list entries picked intra-round: one [C, K, C] compare, two
                # masked reductions (also reused for the cleank carry update)
                cmp = topi[:, :, None] == c[None, None, :]  # [C, K, C(j)]
                chosen_before = (cmp & (jlt & act[None, :])[:, None, :]).any(2)
                cleank2 = cleank & ~chosen_before
                jf2 = jnp.argmax(cleank2, axis=1)
                vu2 = jnp.where(
                    cleank2.any(axis=1),
                    jnp.take_along_axis(topv, jf2[:, None], 1)[:, 0],
                    neg_inf,
                )
                iu2 = jnp.take_along_axis(topi, jf2[:, None], 1)[:, 0]
                vals_all = jnp.concatenate([M2x, Mij], axis=1)  # [C, 2C]
                nodes_all = jnp.concatenate(
                    [
                        jnp.broadcast_to(dn[None], (C, C)),
                        jnp.broadcast_to(cn[None], (C, C)),
                    ],
                    axis=1,
                )
                best2, cand2 = best_and_cand(vals_all, nodes_all, vu2, iu2)
                t = jnp.where(
                    (best2 > neg_inf) & unc & cvalid, cand2.astype(jnp.int32), -1
                )
            # ---- commit the longest exact prefix ----
            with _subphase("commit"):
                bad = unc & (t != c)
                firstbad = jnp.where(bad.any(), jnp.argmax(bad), C).astype(
                    jnp.int32
                )
                prefix = unc & (idxC < firstbad)
                pact = prefix & (c >= 0)
                out = jnp.where(prefix, c, out)
                ord_ = jnp.where(prefix, nrounds, ord_)  # commit-round ordinal
                committed = committed | prefix
                # stale list entries: nodes picked by the committed prefix
                cleank = cleank & ~(cmp & pact[None, None, :]).any(2)
                # per-node committed adds this round (sum over the prefix's
                # pods; one add per node — int32, exact)
                Epact = E & pact[:, None]  # [C(k), C(j)]
                adds = (Epact[:, :, None] * creq[:, None, :]).sum(axis=0)  # [C,R]
                minpos = jnp.where(Epact, idxC[:, None], C).min(axis=0)  # [C(j)]
                owner = pact & (minpos == idxC)  # first chooser of its node
                is_new = owner & ~hasslot
                rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
                newpos = jnp.where(is_new, nd + rank, C)
                dlist = dlist.at[newpos].set(c, mode="drop")
                dsu = dsu.at[newpos].set(used0[cn] + adds, mode="drop")
                dsu = dsu.at[jnp.where(owner & hasslot, sl, C)].add(
                    adds, mode="drop"
                )
                nd = nd + is_new.sum().astype(jnp.int32)
            return committed, out, ord_, cleank, dlist, dsu, nd, nrounds + 1

        st0 = (
            wcom0 if use_inc and wave else jnp.zeros(C, dtype=jnp.bool_),
            wout0 if use_inc and wave else jnp.full(C, -1, dtype=jnp.int32),
            jnp.zeros(C, dtype=jnp.int32),
            jnp.ones((C, K), dtype=jnp.bool_),
            jnp.full(C, -1, dtype=jnp.int32),
            jnp.zeros((C, R), dtype=used0.dtype),
            jnp.int32(0),
            jnp.int32(0),
        )
        with _subphase("round_loop"):
            committed, out, ord_, _, _, _, _, nrounds = lax.while_loop(
                lambda st: ~st[0].all(), round_body, st0
            )
        with _subphase("commit"):
            # wave-committed pods' requests already live in used0 (the wave
            # adds them as it commits) — only this chunk's round-loop
            # commits are new
            newly = out >= 0
            if use_inc and wave:
                newly = newly & ~wcom0
            placed = newly[:, None]
            ucols = jnp.where(newly, out, N)
            used_out = used0.at[ucols].add(
                jnp.where(placed, creq, 0), mode="drop"
            )
        if not use_inc:
            return used_out, (out, nrounds, ord_)
        # patch the carried class hoist at the committed node columns
        # against the NEW usage — exactly what a fresh hoist of the next
        # chunk would compute there (fit/base read per-node usage only);
        # untouched columns keep values computed against unchanged usage,
        # so the carried matrix stays bit-identical to a per-chunk dense
        # re-hoist throughout the scan.  Duplicate committed columns write
        # identical values (same node, same final usage).
        with _subphase("commit"):
            cn_out = jnp.maximum(out, 0)
            col_used = used_out[cn_out]  # [C, R]
            col_alloc = n_alloc_full[cn_out]
            col_fit = jax.vmap(filters.fit_ok, (0, None, None))(
                req_u, col_used, col_alloc
            )  # [U1, C]
            reqd_u = col_used[None, :, :] + req_u[:, None, :]  # [U1, C, R]
            col_base = score_flat(
                reqd_u.reshape(-1, R),
                jnp.broadcast_to(col_alloc[None], reqd_u.shape).reshape(-1, R),
            ).reshape(U1, C)
            col_stat = (
                bitplane.test_cols(stat_full, cn_out, local_n)
                if bitplane.PACK_MASKS else stat_full[:, cn_out]
            )  # [U1, C]
            newv = jnp.where(col_stat & col_fit, col_base, neg_inf)
            t0u = t0u.at[:, ucols].set(newv, mode="drop")
        return (used_out, t0u), (out, nrounds, ord_)

    if use_inc and wave:
        # stage B: the round loop continues the serial order over whatever
        # the wave's block budget left (normally nothing).  The lax.cond
        # makes the skip REAL: when the wave committed every pod the whole
        # chunk scan is skipped at run time, and the analytic ledger
        # (analysis/costmodel.py charges branch 0 of a cond — KTPU009
        # obliges: neither branch holds a collective on this path) prices
        # round_loop at the passthrough, matching the measured collapse.
        def _stage_b(used_w, t0u_w):
            (uf, _), (ch, rd, od) = lax.scan(
                chunk, (used_w, t0u_w),
                (reqs, clss, valids, wcom_c, wout_c),
            )
            return ch, uf, rd, od

        def _skip(used_w, t0u_w):
            return (
                wout_c, used_w,
                jnp.zeros(P // C, dtype=jnp.int32),
                jnp.zeros((P // C, C), dtype=jnp.int32),
            )

        choices, used_final, rounds, ords = lax.cond(
            ~jnp.all(wcom), _stage_b, _skip, used_wave, t0u_wave
        )
    elif use_inc:
        (used_final, _), (choices, rounds, ords) = lax.scan(
            chunk, (used_init, t0u_init), (reqs, clss, valids)
        )
    else:
        used_final, (choices, rounds, ords) = lax.scan(
            chunk, used_init, (reqs, sfs, valids)
        )
    if with_ordinals:
        # global commit ordinal: rounds of all previous chunks + the pod's
        # commit round within its chunk (pods committed in the same round
        # share an ordinal — they were decided by the same device sweep);
        # plus the TOTAL sweep count, the latency-estimate denominator
        # (padding chunks sweep too, so the slice [:n_pods] alone would
        # misattribute their wall share)
        base = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(rounds)[:-1]]
        )
        ords_g = (base[:, None] + ords).reshape(P)
        if use_inc and wave:
            # wave-committed pods carry their BLOCK index (the device sweep
            # that decided them); stage-B rounds number on from the wave's
            # blocks, and the TOTAL sweep count — the latency-estimate
            # denominator — is wave blocks + stage-B rounds
            ords_g = jnp.where(wcom, wordn, ords_g + n_blocks)
            return (choices.reshape(P), used_final, ords_g,
                    rounds.sum() + n_blocks)
        return choices.reshape(P), used_final, ords_g, rounds.sum()
    if with_rounds:
        return choices.reshape(P), used_final, rounds
    return choices.reshape(P), used_final


def _rounds_capable(arr: ClusterArrays, cfg: ScoreConfig) -> bool:
    """The generalized rounds scan (schedule_scan_rounds) serves every stage
    combination the per-pod scan does — it exists for the configs
    `_chunkable` excludes (pairwise/ports/taint-score/node-pref/image), so
    routing tries the cheaper fit-only chunked path first."""
    return arr.P >= _RCHUNK and arr.P % _RCHUNK == 0


def _rounds_routed(arr: ClusterArrays, cfg: ScoreConfig) -> bool:
    ov = os.environ.get("KTPU_FORCE_CHUNKED", "")
    if ov == "1":
        return _rounds_capable(arr, cfg)
    if ov == "0":
        return False
    return jax.default_backend() != "cpu" and _rounds_capable(arr, cfg)


def schedule_scan_rounds(
    arr: ClusterArrays, cfg: ScoreConfig, with_rounds: bool = False,
    with_ordinals: bool = False, axis_name: Optional[str] = None,
    axis_size: int = 1, image_sharded: Optional[bool] = None, inc=None,
):
    """Chunked sequential-commit scan for the FULL stage set — pairwise
    (PodTopologySpread + InterPodAffinity), NodePorts, TaintToleration
    score, preferred NodeAffinity, ImageLocality — BIT-IDENTICAL to
    schedule_scan (tests/test_assign_parity.py — rounds cases).

    schedule_scan_chunked's prefix-commit speculation cannot serve these
    stages: per-pod NormalizeScore couples every node's score through
    max/min scalars over the pod's CURRENT feasible set, and pairwise
    feasibility/raws read per-(term, domain) count state — a committed pod
    perturbs whole domain columns, not just its own node.  This kernel
    keeps the rounds structure but replaces top-K candidate lists with
    RE-HOISTING: every round re-evaluates all (uncommitted) pods of the
    chunk against exact round-start state by vmapping the SAME per-pod row
    functions the plain scan applies per step (pairwise.spread_step,
    interpod_required_ok, interpod_pref_raw, filters.fit_ok, the
    normalization formulas in the same op order) — float32 results are
    bit-identical by construction.  The expensive base (fit+balanced)
    hoist is amortized: computed once per chunk and patched only at
    columns whose usage changed (committed nodes).

    A round then commits pods in three moves:

    1. DISPERSAL SPECULATION: pod i's tentative pick c_i is its rank-th
       best feasible node, rank = earlier uncommitted pods sharing its
       argmax.  Same-spec pods share whole rows and top-k's
       lowest-index-tie order matches the sequential tie-break, so ranks
       walk a tied plateau exactly like the sequential scan does (without
       this, every duplicate argmax truncated the prefix — measured 1.9
       pods/round on BASELINE config 3; 7.5 with it).
    2. EXACT REPAIR: t_i = pod i's TRUE sequential argmax given that
       pods j < i commit c_j — max of (a) the best round-start score over
       nodes NOT picked by the prefix (valid: nothing else changed) and
       (b) the picked nodes rescored under the EXACT prefix usage (an
       int32 associative prefix sum — the same adds in the same order as
       the sequential scan) with round-start raws and scalars; ties break
       to the lowest node index across both sides.
    3. COMMIT: the longest prefix with t == c (speculation confirmed),
       plus the FIRST divergence-only pod committing its exact t.

    The repair itself is valid only while pod i's unpicked scores and
    normalization scalars are round-start-stable, which two HARD
    interference conditions guard (they truncate the prefix instead):

      - share(i, j): j's state writes touch terms i reads.  Writes:
        cnt/total at j's matched terms, anti at j's own anti terms,
        pref-own at j's preferred + (hpaw) required-affinity terms.
        Reads: i's spread/affinity/anti terms (cnt, total), i's matched
        terms (anti for the symmetric filter, pref-own for the symmetric
        score half), i's preferred-affinity terms (cnt).  Precomputed per
        chunk as [C, T] incidence matmuls.  Any shared term can move i's
        raws or masks ANYWHERE (domain columns, min_match, the waiver), so
        this is the coarse gate.  Overlapping host ports gate the same
        way (j's commit flips i's port mask at c_j).
      - a normalization-scalar hazard: c_j was feasible for i, j's commit
        makes it fit-infeasible, AND c_j attains one of i's normalization
        extremes (spread/taint max with max > 0, node-affinity max > 0,
        inter-pod max/min with max > min) — dropping a non-extreme node
        cannot move any scalar, and scalars are the only remaining
        cross-node coupling (same-node picks and score-beats, the old
        truncation conditions, are now handled EXACTLY by the repair).

    A wrong speculation or hard interference only SHORTENS the committed
    prefix (decisions re-derive next round from freshly committed state),
    so conservatism costs rounds, never correctness; the first uncommitted
    pod has no active predecessor — its repair is trivially its argmax —
    so every round commits >= 1 pod, bounding the loop at C rounds.
    Worst case (every pod sharing one term) degrades toward per-pod
    stepping; the expected prefix on mixed workloads is set by the
    birthday structure of term collisions within a chunk (measured on
    BASELINE config 3 at 10k pods x 5k nodes: 17.2 rounds/chunk mean, 32
    max; see tests/test_assign_parity.py — rounds diagnostic).

    State layout: the outer chunk scan carries the live cluster state
    (used[N,R], cnt/anti/pref_node[T,N], total_t[T], ports[N,PT]); the
    inner while_loop additionally carries the patched base/fit hoists
    [C, N].  All count updates are integer-valued f32 / int32 scatter-adds
    — order-independent and exact below 2^24.

    SHARDED EXECUTION (axis_name set, parallel/sharded.py): unlike the
    chunked kernel (whose per-chunk hoist gathers once), the rounds kernel
    re-hoists INSIDE the round loop, so the stitching happens per round and
    stays exactly schedule_scan-shaped — per-node score math never crosses
    shards:

      - the [C, Nl] re-hoist (spread/interpod vmaps, base patch) and the
        [T, Nl] count state are shard-local;
      - per-pod NormalizeScore scalars stitch with pmax (same _rmax the
        per-pod scan uses), the argmax/lowest-index tie-break with
        pmax + pmin over global node ids;
      - dispersal speculation merges shard-local top-Zr lists into the
        global top-Zr (_global_top_k — provably identical values/ids/ties);
      - the exact repair reads only CANDIDATE columns ([C, C]-sized), each
        gathered from its owner shard via psum (_gather_cols);
      - commits broadcast the chosen node's per-term domain column from the
        owner shard via psum (_gather_at_nodes — the schedule_scan pattern)
        and each shard scatter-adds its own [T, Nl] columns.

    The [N, R] usage array is all-gathered once per step and carried
    replicated (tiny next to the [T, N]/[P, N] state, and the repair needs
    arbitrary candidate rows of it every round).

    INCREMENTAL MODE (`inc` = ops/incremental.py — IncState): the per-pod
    usage-independent hoists (static feasibility, eligibility, taint /
    node-affinity / image raws) arrive precomputed per CLASS (resident
    across warm cycles) and are gathered [C, Nl] per chunk through the
    class index; the fit+balanced base hoist [U1, Nl] arrives vs
    cycle-start usage, is carried across chunks in the OUTER scan, and is
    patched at committed columns per round at class level (O(U1·C·R)
    instead of the per-chunk O(C·Nl·R) base_at re-hoist).  Per-pod rows
    are class-row gathers — bit-identical by construction, so decisions
    match the dense path exactly (tests/test_incremental.py)."""
    use_inc = inc is not None
    TRACE_COUNTS[
        ("sharded_rounds" if axis_name else "rounds")
        + ("_inc" if use_inc else "")
    ] += 1
    local_n = arr.N
    if axis_name:
        base = lax.axis_index(axis_name).astype(jnp.int32) * local_n
        N = local_n * axis_size
        n_alloc_full = lax.all_gather(
            arr.node_alloc, axis_name, axis=0, tiled=True
        )
        used_init = lax.all_gather(arr.node_used, axis_name, axis=0, tiled=True)
    else:
        base = jnp.int32(0)
        N = local_n
        n_alloc_full = arr.node_alloc
        used_init = arr.node_used
    my_nodes = base + jnp.arange(local_n, dtype=jnp.int32)
    P, R = arr.P, arr.R
    C = _RCHUNK
    res = cfg.score_resources
    neg_inf = -jnp.inf
    MAXS = MAX_NODE_SCORE
    idxC = jnp.arange(C, dtype=jnp.int32)
    jlt = idxC[None, :] < idxC[:, None]  # [i, j]: j < i

    pw = cfg.enable_pairwise
    ips = pw and cfg.enable_interpod_score
    T = arr.term_counts0.shape[0]
    D = arr.term_counts0.shape[1] - 1
    dom_by_term = arr.node_dom[arr.term_key]  # i32[T, N]
    has_key_all = dom_by_term < D
    if use_inc:
        # usage-independent hoists (sf / elig / taint / node-affinity /
        # image raws) arrive precomputed per CLASS and resident across
        # cycles — the [P, Nl] preludes below never trace
        U1 = inc.req_u.shape[0]
        req_u = inc.req_u
        img_on = inc.img_u is not None
    else:
        img_on = _image_on(arr, cfg, image_sharded)
        with _subphase("hoist"):
            tm = filters.term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)
            if not bitplane.PACK_MASKS:
                # dense escape hatch; the packed hoist runs chunk-wise at
                # the xs assembly below so no [P, Nl] transient traces
                nodesel = filters.node_selection_ok_from(tm, arr)
                pin = arr.pod_nodename[:, None]
                nodename_ok = jnp.where(
                    pin == -1, True, pin == my_nodes[None, :]
                )
                sf = (
                    arr.node_valid[None, :]
                    & arr.pod_valid[:, None]
                    & filters.taints_ok(arr)
                    & nodesel
                    & nodename_ok
                )
    n_alloc = arr.node_alloc

    def score_flat(requested, alloc):
        return cfg.fit_weight * fit_score(
            requested, alloc, cfg
        ) + cfg.balanced_weight * balanced_allocation(requested, alloc, res)

    def seg(x):  # [P, ...] -> [P//C, C, ...]
        return x.reshape(P // C, C, *x.shape[1:])

    xs = {
        "req": seg(arr.pod_req),
        "valid": seg(arr.pod_valid),
    }
    if use_inc:
        xs["cls"] = seg(inc.cls)
    else:
        if bitplane.PACK_MASKS:
            # chunk-wise packed static hoist (same discipline as the
            # chunked kernel): [C, Nl] dense blocks pack immediately, the
            # scan inputs ride as [P//C, C, Wl] uint32 word planes
            with _subphase("hoist"):
                pod_blocks = (
                    seg(arr.pod_terms), seg(arr.pod_has_sel),
                    seg(arr.pod_tol_ns), seg(arr.pod_nodename),
                    seg(arr.pod_valid),
                )

                def _sf_block(px):
                    pt, ph, ptol, pnn, pv = px
                    sfb, nsb = filters.static_feasible_rows(
                        tm, arr.node_valid, arr.node_taint_ns, my_nodes,
                        pt, ph, ptol, pnn, pv,
                    )
                    out = (bitplane.pack(sfb),)
                    if pw:
                        out += (
                            bitplane.pack(nsb & arr.node_valid[None, :]),
                        )
                    return out

                packed = lax.map(_sf_block, pod_blocks)
                xs["sf"] = packed[0]
                if pw:
                    xs["elig"] = packed[1]
        else:
            xs["sf"] = seg(sf)
            if pw:
                xs["elig"] = seg(nodesel & arr.node_valid[None, :])
        if cfg.enable_taint_score:
            with _subphase("hoist"):
                xs["traw"] = seg(taint_prefer_counts(arr))
        if cfg.enable_node_pref:
            with _subphase("hoist"):
                xs["naraw"] = seg(_preferred_node_affinity_raw(arr, tm))
        if img_on:
            xs["img"] = seg(arr.image_score)
    if pw:
        xs.update(
            spread_t=seg(arr.pod_spread_terms),
            skew=seg(arr.pod_spread_maxskew),
            hard=seg(arr.pod_spread_hard),
            aff=seg(arr.pod_aff_terms),
            anti=seg(arr.pod_anti_terms),
            mt=seg(arr.pod_match_terms),
            mv=seg(arr.pod_match_vals),
            aself=seg(arr.pod_aff_self),
        )
        if ips:
            xs["pref_t"] = seg(arr.pod_pref_aff_terms)
            xs["pref_w"] = seg(arr.pod_pref_aff_w)
    if cfg.enable_ports:
        xs["ports"] = seg(arr.pod_ports)

    def slot_indicator(ids, w=None):
        """[C, slots] padded ids -> f32[C, T] incidence (1 where the pod
        carries the term)."""
        on = (ids >= 0) if w is None else ((ids >= 0) & (w != 0))
        M = jnp.zeros((C, max(T, 1)), dtype=jnp.float32)
        return M.at[idxC[:, None], jnp.maximum(ids, 0)].max(
            on.astype(jnp.float32)
        )

    def chunk(carry, cx):
        if use_inc:
            (used0, cnt_node, anti_node, pref_node, total_t, ports_used,
             base0_c, fit0_c) = carry
        else:
            used0, cnt_node, anti_node, pref_node, total_t, ports_used = carry
        creq, cvalid = cx["req"], cx["valid"]
        if use_inc:
            # per-pod rows of the resident class hoists (identical rows by
            # construction — api/delta.py _pod_side scatters per spec);
            # pod_valid folds back in per pod (stat_u excludes it so the
            # resident state survives the gang fixpoint's revocations)
            ccls = cx["cls"]
            # packed class planes unpack at this per-chunk frontier
            # ([C, Nl] dense transients, C = _RCHUNK); bf16-stored raws
            # upcast to f32 before any normalization reduction
            stat_rows = inc.stat_u[ccls]
            if bitplane.PACK_MASKS:
                stat_rows = bitplane.unpack(stat_rows, local_n)
            csf = stat_rows & cvalid[:, None]
            celig = inc.elig_u[ccls] if pw else None
            if pw and bitplane.PACK_MASKS:
                celig = bitplane.unpack(celig, local_n)
            ctraw = (
                inc.traw_u[ccls].astype(jnp.float32)
                if cfg.enable_taint_score else None
            )
            cnaraw = (
                inc.naraw_u[ccls].astype(jnp.float32)
                if cfg.enable_node_pref else None
            )
            cimg = inc.img_u[ccls].astype(jnp.float32) if img_on else None
        else:
            csf = cx["sf"]
            celig = cx["elig"] if pw else None
            if bitplane.PACK_MASKS:
                csf = bitplane.unpack(csf, local_n)
                if pw:
                    celig = bitplane.unpack(celig, local_n)
            ctraw = (
                cx["traw"].astype(jnp.float32)
                if cfg.enable_taint_score else None
            )
            cnaraw = (
                cx["naraw"].astype(jnp.float32)
                if cfg.enable_node_pref else None
            )
            cimg = cx["img"].astype(jnp.float32) if img_on else None

        # --- per-chunk static: interference incidence [C, C] ---
        with _subphase("hoist"):
            if pw:
                rd = slot_indicator(cx["spread_t"]) + slot_indicator(
                    cx["aff"]
                ) + slot_indicator(cx["anti"])
                wr_cnt = slot_indicator(cx["mt"], cx["mv"])
                rd_anti = slot_indicator(cx["mt"])
                wr_anti = slot_indicator(cx["anti"])
                share = (rd @ wr_cnt.T + rd_anti @ wr_anti.T) > 0.0
                if ips:
                    rd_pref = slot_indicator(cx["pref_t"])
                    wr_pref = slot_indicator(cx["pref_t"])
                    if cfg.hard_pod_affinity_weight:
                        wr_pref = jnp.maximum(
                            wr_pref, slot_indicator(cx["aff"])
                        )
                    share |= (
                        rd_pref @ wr_cnt.T + rd_anti @ wr_pref.T
                    ) > 0.0
            else:
                share = jnp.zeros((C, C), dtype=jnp.bool_)
            if cfg.enable_ports:
                pf = cx["ports"].astype(jnp.float32)
                share |= (pf @ pf.T) > 0.0

        # --- chunk-start base hoist (patched per round at dirty columns) ---
        def base_at(used):
            # `used` is the FULL [N, R] array; the hoist reads this shard's
            # node slice only — [C, Nl] blocks, elementwise, bit-identical
            # to the same columns of the dense hoist
            with _subphase("hoist"):
                if axis_name:
                    used_l = lax.dynamic_slice_in_dim(
                        used, base, local_n, axis=0
                    )
                else:
                    used_l = used
                requested = used_l[None, :, :] + creq[:, None, :]
                fit = jax.vmap(filters.fit_ok, (0, None, None))(
                    creq, used_l, n_alloc
                )
                b = cfg.fit_weight * jax.vmap(
                    lambda rq, al: fit_score(rq, al, cfg), (0, None)
                )(requested, n_alloc) + cfg.balanced_weight * jax.vmap(
                    balanced_allocation, (0, None, None)
                )(requested, n_alloc, res)
                return b, fit

        if not use_inc:
            base0_init, fit0_init = base_at(used0)
        else:
            # the class base hoist rides the OUTER carry — computed once
            # per cycle (ops/incremental.py) and patched at committed
            # columns below, never re-hoisted per chunk
            base0_init, fit0_init = base0_c, fit0_c

        def round_body(st):
            (committed, out, ord_, base0, fit0, used, cnt_node, anti_node,
             pref_node, total_t, ports_used, nrounds) = st
            unc = ~committed

            # ---- exact re-hoist vs round-start state ----
            with _subphase("score"):
                if use_inc:
                    # per-pod rows of the patched class matrices [U1, Nl]
                    fit0_p = fit0[ccls]
                    base0_p = base0[ccls]
                else:
                    fit0_p, base0_p = fit0, base0
                feasible = csf & fit0_p
                if cfg.enable_ports:
                    feasible &= jax.vmap(pairwise.ports_ok, (None, 0))(
                        ports_used, cx["ports"]
                    )
                if pw:
                    spread_ok, spread_raw = jax.vmap(
                        partial(pairwise.spread_step, axis_name=axis_name),
                        (None, None, 0, 0, 0, 0),
                    )(cnt_node, has_key_all, cx["spread_t"], cx["skew"],
                      cx["hard"], celig)
                    interpod_ok = jax.vmap(
                        pairwise.interpod_required_ok,
                        (None, None, None, None, 0, 0, 0, 0, 0),
                    )(cnt_node, anti_node, total_t, has_key_all, cx["aff"],
                      cx["anti"], cx["mt"], cx["mv"], cx["aself"])
                    feasible &= spread_ok & interpod_ok
            with _subphase("normalize"):
                total = base0_p
                # per-pod NormalizeScore scalars over the CURRENT feasible set,
                # accumulated in the plain scan's stage order (float parity);
                # under sharding the scalars stitch with pmax, like the scan
                if cfg.enable_taint_score:
                    t_mx = _rmax(jnp.where(feasible, ctraw, 0.0), axis_name)
                    total = total + cfg.taint_weight * jnp.where(
                        (t_mx > 0)[:, None],
                        MAXS - MAXS * ctraw / t_mx[:, None],
                        MAXS,
                    )
                if cfg.enable_node_pref:
                    na_mx = _rmax(jnp.where(feasible, cnaraw, 0.0), axis_name)
                    total = total + cfg.node_affinity_weight * jnp.where(
                        (na_mx > 0)[:, None],
                        cnaraw * MAXS / na_mx[:, None],
                        0.0,
                    )
                if pw:
                    s_mx = _rmax(jnp.where(feasible, spread_raw, 0.0), axis_name)
                    total = total + cfg.spread_weight * jnp.where(
                        (s_mx > 0)[:, None],
                        MAXS - MAXS * spread_raw / s_mx[:, None],
                        MAXS,
                    )
                if ips:
                    ip_raw = jax.vmap(
                        pairwise.interpod_pref_raw,
                        (None, None, None, 0, 0, 0, 0),
                    )(cnt_node, pref_node, has_key_all, cx["pref_t"],
                      cx["pref_w"], cx["mt"], cx["mv"])
                    ip_mx = _rmax(
                        jnp.where(feasible, ip_raw, neg_inf), axis_name
                    )
                    ip_mn = -_rmax(
                        jnp.where(feasible, -ip_raw, neg_inf), axis_name
                    )
                    total = total + cfg.interpod_weight * jnp.where(
                        (ip_mx > ip_mn)[:, None],
                        MAXS * (ip_raw - ip_mn[:, None])
                        / (ip_mx[:, None] - ip_mn[:, None]),
                        0.0,
                    )
                if img_on:
                    total = total + cfg.image_weight * cimg
                total = jnp.where(feasible, total, neg_inf)
                best = _rmax(total, axis_name)
                cand = _rmin(
                    jnp.where(
                        (total == best[:, None]) & feasible,
                        my_nodes[None, :], _INT_MAX,
                    ),
                    axis_name,
                )
                c0 = jnp.where(
                    (best > neg_inf) & cvalid, cand.astype(jnp.int32), -1
                )
            # ---- dispersal speculation: same-choice pods would otherwise
            # truncate the prefix at every duplicate (measured 1.9 pods/
            # round on BASELINE config 3 without it).  Pod i speculates its
            # rank-th best feasible node, rank = earlier uncommitted pods
            # sharing its argmax — same-spec pods share whole rows (and
            # top-k's lowest-index-tie order matches the sequential
            # tie-break), so ranks walk the plateau exactly like the
            # sequential scan does.  A wrong guess is caught by the exact
            # repair below and only shortens the prefix. ----
            with _subphase("speculate"):
                same0 = (
                    (c0[:, None] == c0[None, :])
                    & (c0[None, :] >= 0)
                    & unc[None, :]
                )
                rank = (same0 & jlt).sum(axis=1).astype(jnp.int32)
                Zr = min(32, N)
                topv, topi = _global_top_k(total, Zr, axis_name, base)
                sel = jnp.minimum(rank, Zr - 1)[:, None]
                v_sel = jnp.take_along_axis(topv, sel, 1)[:, 0]
                c_sp = jnp.take_along_axis(topi, sel, 1)[:, 0].astype(jnp.int32)
                c = jnp.where(
                    unc & (c0 >= 0) & (rank > 0) & (rank < Zr)
                    & (v_sel > neg_inf),
                    c_sp,
                    c0,
                )

            # ---- exact repair under the intra-round prefix ----
            def repair(c):
                """(t, hard) for speculation c: t_i = pod i's TRUE
                sequential argmax given pods j < i commit c_j; hard_i =
                the repair's premises are void for i (term-sharing or an
                extreme-attaining feasibility drop among its prefix)."""
                with _subphase("repair"):
                    act = unc & (c >= 0)
                    cn = jnp.maximum(c, 0)
                    E = (c[:, None] == c[None, :]) & act[:, None]
                    T3 = E[:, :, None] * creq[:, None, :]
                    cum = lax.associative_scan(jnp.add, T3, axis=0) - T3
                    ca = n_alloc_full[cn]  # [C, R]
                    uij = used[cn][None, :, :] + cum  # [C(i), C(j), R]
                    fitij = jax.vmap(filters.fit_ok, (0, 0, None))(creq, uij, ca)
                    reqij = uij + creq[:, None, :]
                    shape3 = reqij.shape
                    baseij = score_flat(
                        reqij.reshape(-1, R),
                        jnp.broadcast_to(ca[None], shape3).reshape(-1, R),
                    ).reshape(C, C)
                    # round-start raws at the candidate nodes: each [C, C] block
                    # gathered from its owner shard (shard-local values, psum
                    # broadcast — no full-matrix traffic)
                    feas0_at = _gather_cols(feasible, cn, axis_name, base, local_n)
                    newtot = baseij
                    extreme_at = jnp.zeros((C, C), dtype=jnp.bool_)
                    if cfg.enable_taint_score:
                        r_at = _gather_cols(ctraw, cn, axis_name, base, local_n)
                        newtot = newtot + cfg.taint_weight * jnp.where(
                            (t_mx > 0)[:, None],
                            MAXS - MAXS * r_at / t_mx[:, None],
                            MAXS,
                        )
                        extreme_at |= (t_mx > 0)[:, None] & (r_at == t_mx[:, None])
                    if cfg.enable_node_pref:
                        r_at = _gather_cols(
                            cnaraw, cn, axis_name, base, local_n
                        )
                        newtot = newtot + cfg.node_affinity_weight * jnp.where(
                            (na_mx > 0)[:, None],
                            r_at * MAXS / na_mx[:, None],
                            0.0,
                        )
                        extreme_at |= (na_mx > 0)[:, None] & (
                            r_at == na_mx[:, None]
                        )
                    if pw:
                        r_at = _gather_cols(
                            spread_raw, cn, axis_name, base, local_n
                        )
                        newtot = newtot + cfg.spread_weight * jnp.where(
                            (s_mx > 0)[:, None],
                            MAXS - MAXS * r_at / s_mx[:, None],
                            MAXS,
                        )
                        extreme_at |= (s_mx > 0)[:, None] & (r_at == s_mx[:, None])
                    if ips:
                        r_at = _gather_cols(ip_raw, cn, axis_name, base, local_n)
                        newtot = newtot + cfg.interpod_weight * jnp.where(
                            (ip_mx > ip_mn)[:, None],
                            MAXS * (r_at - ip_mn[:, None])
                            / (ip_mx[:, None] - ip_mn[:, None]),
                            0.0,
                        )
                        extreme_at |= (ip_mx > ip_mn)[:, None] & (
                            (r_at == ip_mx[:, None]) | (r_at == ip_mn[:, None])
                        )
                    if img_on:
                        newtot = newtot + cfg.image_weight * _gather_cols(
                            cimg, cn, axis_name, base, local_n
                        )
                    newtot = jnp.where(feas0_at & fitij, newtot, neg_inf)
                    dropped = feas0_at & ~fitij
                    hard = (
                        (share | (dropped & extreme_at)) & jlt & act[None, :]
                    ).any(axis=1)
                    # unpicked nodes keep round-start scores; picked nodes take
                    # the rescored newtot
                    O = ((c[:, None] == my_nodes[None, :]) & act[:, None]).astype(
                        jnp.float32
                    )  # [C(j), N] pick indicator
                    picked_before = (jlt.astype(jnp.float32) @ O) > 0.0  # [C, Nl]
                    av = _rmax(jnp.where(picked_before, neg_inf, total), axis_name)
                    a_n = _rmin(
                        jnp.where(
                            (total == av[:, None]) & ~picked_before,
                            my_nodes[None, :],
                            _INT_MAX,
                        ),
                        axis_name,
                    )
                    Mj = jnp.where(act[None, :] & jlt, newtot, neg_inf)
                    vb = jnp.max(Mj, axis=1)
                    b_n = jnp.where(Mj == vb[:, None], cn[None, :], _INT_MAX).min(
                        axis=1
                    )
                    t_val = jnp.maximum(av, vb)
                    t_n = jnp.where(
                        vb > av, b_n,
                        jnp.where(av > vb, a_n, jnp.minimum(a_n, b_n)),
                    )
                    t = jnp.where(
                        (t_val > neg_inf) & cvalid, t_n.astype(jnp.int32), -1
                    )
                    return t, hard

            # iterate speculate -> repair: a wrong guess at pod k corrupts
            # only guesses AFTER k, and its own repair is exact, so feeding
            # t back as the next speculation converges the prefix toward
            # the hard-interference bound instead of stopping at the first
            # divergence (the commit rule below revalidates the FINAL c, so
            # iterations only improve throughput, never correctness)
            for _ in range(_REPAIR_ITERS - 1):
                t, hard = repair(c)
                c = jnp.where(unc, t, c)
            t, hard = repair(c)

            # ---- commit: the longest prefix whose speculation matched the
            # exact repair, plus the FIRST divergence-only pod committing
            # its exact t (hard interference voids t, so not that one) ----
            with _subphase("commit"):
                div = t != c
                bad = unc & (hard | div)
                firstbad = jnp.where(bad.any(), jnp.argmax(bad), C).astype(
                    jnp.int32
                )
                fb_commit = (idxC == firstbad) & unc & ~hard
                c_final = jnp.where(fb_commit, t, c)
                prefix = unc & (idxC < firstbad)
                commit_set = prefix | fb_commit
                pact = commit_set & (c_final >= 0)
                cn_final = jnp.maximum(c_final, 0)
                out = jnp.where(commit_set, c_final, out)
                ord_ = jnp.where(commit_set, nrounds, ord_)  # commit ordinal
                committed = committed | commit_set

                # ---- absorb the committed picks into the live state ----
                ucols = jnp.where(pact, c_final, N)  # N = drop sentinel (GLOBAL)
                adds = jnp.zeros((N, R), dtype=used.dtype).at[ucols].add(
                    jnp.where(pact[:, None], creq, 0), mode="drop"
                )
                used = used + adds
                # patch base/fit at the dirtied columns against the NEW usage
                col_used = used[cn_final]  # [C, R] (committed cols; others dropped)
                col_alloc = n_alloc_full[cn_final]
                if use_inc:
                    # class-level column recompute: one [U1, C] block replaces
                    # the per-pod [C, C] one (per-pod rows are class-row
                    # gathers, so the scattered values are identical)
                    col_req = col_used[None, :, :] + req_u[:, None, :]  # [U1,C,R]
                    col_fit = jax.vmap(
                        lambda rq: filters.fit_ok(rq, col_used, col_alloc)
                    )(req_u)
                    col_base = score_flat(
                        col_req.reshape(-1, R),
                        jnp.broadcast_to(
                            col_alloc[None], col_req.shape
                        ).reshape(-1, R),
                    ).reshape(U1, C)
                else:
                    col_req = col_used[None, :, :] + creq[:, None, :]  # [C, C, R]
                    col_fit = jax.vmap(
                        lambda rq: filters.fit_ok(rq, col_used, col_alloc)
                    )(creq)
                    col_base = score_flat(
                        col_req.reshape(-1, R),
                        jnp.broadcast_to(col_alloc[None], col_req.shape).reshape(
                            -1, R
                        ),
                    ).reshape(C, C)
                if axis_name:
                    # each shard patches only the columns it owns; foreign and
                    # sentinel ids map to local_n and drop (duplicate committed
                    # columns write identical values — same node, same usage)
                    lucols = jnp.where(
                        (ucols >= base) & (ucols < base + local_n),
                        ucols - base, local_n,
                    )
                else:
                    lucols = ucols
                base0 = base0.at[:, lucols].set(col_base, mode="drop")
                fit0 = fit0.at[:, lucols].set(col_fit, mode="drop")
                if cfg.enable_ports:
                    ports_used = ports_used.at[lucols].max(
                        cx["ports"] & pact[:, None], mode="drop"
                    )
                if pw:
                    def scatter_rows(state, ids, w):
                        """state[T, N] += w * (dom matches the pod's chosen
                        domain), rows = the (pod, slot) flattening.  Under
                        sharding the chosen node's domain per term comes from
                        the owner shard (psum broadcast — the schedule_scan
                        commit pattern) and each shard adds to its own
                        [*, Nl] columns."""
                        tids = jnp.maximum(ids, 0).reshape(-1)  # [C*S]
                        nodes = jnp.broadcast_to(
                            cn_final[:, None], ids.shape
                        ).reshape(-1)
                        wf = w.reshape(-1)
                        dcol = _gather_at_nodes(
                            dom_by_term, tids, nodes, axis_name, base, local_n
                        )  # [C*S]
                        same = dom_by_term[tids] == dcol[:, None]  # [C*S, Nl]
                        return state.at[tids].add(wf[:, None] * same), (
                            tids, dcol, wf
                        )

                    w_mt = jnp.where(
                        (cx["mt"] >= 0) & pact[:, None], cx["mv"], 0.0
                    )
                    cnt_node, (tids_mt, dcol_mt, wf_mt) = scatter_rows(
                        cnt_node, cx["mt"], w_mt
                    )
                    total_t = total_t.at[tids_mt].add(
                        wf_mt * (dcol_mt < D)
                    )
                    w_an = (
                        (cx["anti"] >= 0) & pact[:, None]
                    ).astype(anti_node.dtype)
                    anti_node, _ = scatter_rows(anti_node, cx["anti"], w_an)
                    if ips:
                        w_pf = jnp.where(
                            (cx["pref_t"] >= 0) & pact[:, None],
                            cx["pref_w"], 0.0,
                        )
                        pref_node, _ = scatter_rows(
                            pref_node, cx["pref_t"], w_pf
                        )
                        if cfg.hard_pod_affinity_weight:
                            w_ha = jnp.where(
                                (cx["aff"] >= 0) & pact[:, None],
                                jnp.float32(cfg.hard_pod_affinity_weight),
                                0.0,
                            )
                            pref_node, _ = scatter_rows(
                                pref_node, cx["aff"], w_ha
                            )
            return (committed, out, ord_, base0, fit0, used, cnt_node,
                    anti_node, pref_node, total_t, ports_used, nrounds + 1)

        st0 = (
            jnp.zeros(C, dtype=jnp.bool_),
            jnp.full(C, -1, dtype=jnp.int32),
            jnp.zeros(C, dtype=jnp.int32),
            base0_init,
            fit0_init,
            used0, cnt_node, anti_node, pref_node, total_t, ports_used,
            jnp.int32(0),
        )
        with _subphase("round_loop"):
            st = lax.while_loop(lambda s: ~s[0].all(), round_body, st0)
        (_, out, ord_, base0_f, fit0_f, used, cnt_node, anti_node, pref_node,
         total_t, ports_used, nrounds) = st
        carry_out = (used, cnt_node, anti_node, pref_node, total_t, ports_used)
        if use_inc:
            # the patched class hoist flows to the next chunk: committed
            # columns are exact vs the new usage, untouched columns kept
            # values whose inputs did not change — bit-identical to the
            # per-chunk base_at re-hoist
            carry_out = carry_out + (base0_f, fit0_f)
        return carry_out, (out, nrounds, ord_)

    with _subphase("hoist"):
        cnt_node0 = jnp.take_along_axis(arr.term_counts0, dom_by_term, axis=1)
        anti_node0 = jnp.take_along_axis(arr.anti_counts0, dom_by_term, axis=1)
        pref_node0 = jnp.take_along_axis(arr.pref_own0, dom_by_term, axis=1)
        total_t0 = arr.term_counts0[:, :D].sum(axis=1)
    carry0 = (
        used_init, cnt_node0, anti_node0, pref_node0, total_t0,
        arr.node_ports0,
    )
    if use_inc:
        # the carried fit plane is patched per round with mixed set/clear
        # column writes, so it rides DENSE ([U1, Nl] bool — U-scale, tiny);
        # the resident IncState form stays packed
        fit_u0 = (
            bitplane.unpack(inc.fit_u, local_n)
            if bitplane.PACK_MASKS else inc.fit_u
        )
        carry0 = carry0 + (inc.base_u, fit_u0)
    (used_final, *_), (choices, rounds, ords) = lax.scan(chunk, carry0, xs)
    if with_ordinals:
        base = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(rounds)[:-1]]
        )
        return (choices.reshape(P), used_final,
                (base[:, None] + ords).reshape(P), rounds.sum())
    if with_rounds:
        return choices.reshape(P), used_final, rounds
    return choices.reshape(P), used_final


def inc_route_applies(arr, cfg: ScoreConfig) -> bool:
    """Whether this (arr, cfg) routes a kernel that consumes the
    incremental class state at all — callers gate HoistCache.ensure() on
    it so waves that route the plain per-pod scan never pay the [U, N]
    class hoist for nothing."""
    return _chunk_routed(arr, cfg) or _rounds_routed(arr, cfg)


def inc_applicable(arr, cfg: ScoreConfig, inc):
    """Shape/config gate for the incremental class state (ops/incremental.py
    — IncState): None unless the state matches this call's arrays and the
    dedup is non-degenerate (U1 < P; the all-pods-unique wave routes the
    plain dense kernels, making the dedup path a provable no-op).  Pure
    host-side — it decides the jit call's pytree structure."""
    if inc is None:
        return None
    if inc.req_u.shape[0] >= arr.P or inc.cls.shape[0] != arr.P:
        return None
    # node-axis width check reads base_u (always dense f32) — stat/fit/elig
    # ride as packed uint32 words under KTPU_PACK_MASKS, so their last axis
    # is a WORD count, not N
    if inc.base_u.shape[-1] != arr.N or inc.req_u.shape[1] != arr.R:
        return None
    if arr.P % _INC_CHUNK:  # a hand-set KTPU_INC_CHUNK must divide P
        return None
    image_on = cfg.enable_image and arr.image_score.shape[1] == arr.N
    if (
        (cfg.enable_pairwise and inc.elig_u is None)
        or (cfg.enable_taint_score and inc.traw_u is None)
        or (cfg.enable_node_pref and inc.naraw_u is None)
        or (image_on != (inc.img_u is not None))
    ):
        return None
    return inc


def schedule_batch_impl(
    arr: ClusterArrays, cfg: ScoreConfig, inc=None
) -> Tuple[jax.Array, jax.Array]:
    if _chunk_routed(arr, cfg):
        return schedule_scan_chunked(arr, cfg, inc=inc)
    if _rounds_routed(arr, cfg):
        return schedule_scan_rounds(arr, cfg, inc=inc)
    return schedule_scan(arr, cfg, axis_name=None)


schedule_batch = partial(jax.jit, static_argnames=("cfg",))(schedule_batch_impl)

# Donating variants: the step's input device buffers are handed to XLA, so
# node_used [N, R] aliases used_final in place and the [P, N]-scale inputs
# free as soon as the program's last read of them retires — the step's
# intermediates stop DOUBLING peak device memory.  The contract is strict:
# a donated buffer must never be re-read by host code afterwards (the
# encoder's resident-buffer reuse is fundamentally incompatible — callers
# must pair donation with fresh per-wave transfers; api/delta.py —
# encode_device(fresh=True), asserted by tests/test_pipeline_parity.py).
schedule_batch_donated = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(0,)
)(schedule_batch_impl)


def donation_supported() -> bool:
    """Whether the donating kernels should route on this backend.

    KTPU_DONATE=1 forces donation, =0 disables it; default = donate on
    accelerator backends that actually honor it (probed ONCE by donating a
    scratch buffer and checking it was invalidated — backends that merely
    warn and keep the buffer gain nothing, and backends that raise are
    caught the same way, so both take the non-donating fallback).  The CPU
    sim is excluded by default even though its runtime honors donation:
    with no separate device memory there is nothing to save, and the
    donation-induced fresh transfers measurably slow the 2-core fallback —
    KTPU_DONATE=1 still forces it there (the parity/safety tests do)."""
    ov = os.environ.get("KTPU_DONATE", "")
    if ov == "1":
        return True
    if ov == "0":
        return False
    if jax.default_backend() == "cpu":
        return False
    global _DONATION_PROBED
    if _DONATION_PROBED is None:
        try:
            import warnings

            x = jax.device_put(jnp.zeros((2, 2), dtype=jnp.int32))
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                jax.jit(lambda a: a + 1, donate_argnums=(0,))(
                    x
                ).block_until_ready()
            _DONATION_PROBED = bool(x.is_deleted())
        except Exception:  # noqa: BLE001 — a rejecting backend = fallback
            _DONATION_PROBED = False
    return _DONATION_PROBED


_DONATION_PROBED: Optional[bool] = None


def schedule_batch_routed(arr, cfg: ScoreConfig, donate: bool, mesh=None,
                          inc=None):
    """schedule_batch with donation routed per call.  `donate` is the
    caller's RESOLVED decision (resolve defaults with donation_supported();
    an explicit True forces the donating kernel — tests do, even on the CPU
    sim).  The "donated buffers were not usable" warning is expected noise
    on this kernel (most inputs cannot alias the two outputs; donation
    still frees them early) and is suppressed here only.

    `mesh` (jax.sharding.Mesh with >1 device) runs the SAME route — chunked
    / rounds / per-pod scan — node-axis sharded under shard_map
    (parallel/sharded.py — sharded_schedule_batch_routed), bit-identical
    decisions; node counts not divisible by the mesh pad with permanently
    invalid nodes (parallel/mesh.py — pad_nodes).

    `inc` (ops/incremental.py — HoistCache.ensure) is the resident
    equivalence-class hoist state.  It enters the jit as a SEPARATE,
    never-donated argument — only the per-wave ClusterArrays transfers are
    donated, so a donated step can never consume the resident cache (the
    donation-aliasing rule, PARITY.md)."""
    if mesh is not None and getattr(mesh, "size", 1) > 1:
        from ..parallel.sharded import sharded_schedule_batch_routed

        return sharded_schedule_batch_routed(
            arr, cfg, mesh, donate=donate, inc=inc
        )
    inc = inc_applicable(arr, cfg, inc)
    if donate:
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return schedule_batch_donated(arr, cfg, inc)
    return schedule_batch(arr, cfg, inc)


def schedule_batch_ordinals_impl(arr: ClusterArrays, cfg: ScoreConfig,
                                 inc=None):
    """schedule_batch + (per-pod COMMIT ORDINAL i32[P], total sweeps i32):
    the ordinal is the index of the sequential device sweep that decided
    each pod (the scan step on the per-pod path; the global round on the
    chunked paths); `sweeps` is the kernel's TOTAL sweep count including
    pod-axis padding.  Together they turn a wave's single wall time into a
    per-pod latency distribution — pod i's decision was available
    ~(ordinal_i + 1) / sweeps of the way through the kernel step
    (BASELINE.md p99 scheduling latency; round-3 verdict missing #5)."""
    if _chunk_routed(arr, cfg):
        return schedule_scan_chunked(arr, cfg, with_ordinals=True, inc=inc)
    if _rounds_routed(arr, cfg):
        return schedule_scan_rounds(arr, cfg, with_ordinals=True, inc=inc)
    choices, used = schedule_scan(arr, cfg, axis_name=None)
    return choices, used, jnp.arange(arr.P, dtype=jnp.int32), jnp.int32(arr.P)


schedule_batch_ordinals = partial(jax.jit, static_argnames=("cfg",))(
    schedule_batch_ordinals_impl
)

schedule_batch_ordinals_donated = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(0,)
)(schedule_batch_ordinals_impl)


def schedule_batch_ordinals_routed(arr, cfg: ScoreConfig, donate: bool,
                                   mesh=None, inc=None):
    """schedule_batch_ordinals with the same donation routing + warning
    policy as schedule_batch_routed (`donate` = the caller's resolved
    decision), the same `mesh=` scale-out path, and the same never-donated
    `inc=` incremental-hoist argument."""
    if mesh is not None and getattr(mesh, "size", 1) > 1:
        from ..parallel.sharded import sharded_schedule_batch_routed

        return sharded_schedule_batch_routed(
            arr, cfg, mesh, donate=donate, with_ordinals=True, inc=inc
        )
    inc = inc_applicable(arr, cfg, inc)
    if donate:
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return schedule_batch_ordinals_donated(arr, cfg, inc)
    return schedule_batch_ordinals(arr, cfg, inc)
