"""Batched assignment with sequential-commit semantics — L3 (the hard part).

The reference schedules one pod per cycle; placing pod i mutates NodeInfo before
pod i+1 is considered (pkg/scheduler/schedule_one.go — ScheduleOne + the assume
cache, backend/cache/cache.go — AssumePod).  To reproduce those semantics in one
XLA program, everything capacity-independent (static feasibility, raw score
counts) is evaluated for the whole batch up front as [P, N] matrices, and a
`lax.scan` over pods (in activeQ order == array order) re-evaluates only the
capacity-dependent terms per step:

  - NodeResourcesFit.Filter against the running node_used
  - LeastAllocated / BalancedAllocation scores against used + this pod's request
  - per-pod NormalizeScore over the *currently* feasible set

Host selection is argmax of the weighted sum; ties break to the lowest node
index.  (The reference's selectHost — schedule_one.go — picks randomly among
equal-score nodes; this framework is deterministic by design, the "full-scoring
deterministic mode" deviation called out in SURVEY.md §7 hard part 1.  The
oracle applies the identical rule, so parity is exact within the framework.)
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..api.snapshot import ClusterArrays
from . import filters
from .scores import ScoreConfig, balanced_allocation, least_allocated, normalize_reverse, taint_prefer_counts


def schedule_batch_impl(arr: ClusterArrays, cfg: ScoreConfig) -> Tuple[jax.Array, jax.Array]:
    """Schedule every pending pod in the snapshot.

    Returns (assignment i32[P] — node index or -1 unschedulable,
             node_used i32[N, R] — capacity state after all commits).
    """
    sf = filters.static_feasible(arr)  # [P, N]
    pref = taint_prefer_counts(arr)  # [P, N]
    n_alloc = arr.node_alloc

    def step(used, xs):
        req, feas_row, pref_row, valid = xs
        feasible = feas_row & filters.fit_ok(req, used, n_alloc)
        requested = used + req[None, :]
        total = (
            cfg.fit_weight * least_allocated(requested, n_alloc, cfg.score_resources)
            + cfg.balanced_weight
            * balanced_allocation(requested, n_alloc, cfg.score_resources)
            + cfg.taint_weight * normalize_reverse(pref_row, feasible)
        )
        total = jnp.where(feasible, total, -jnp.inf)
        schedulable = feasible.any() & valid
        choice = jnp.where(schedulable, jnp.argmax(total).astype(jnp.int32), -1)
        placed = (jnp.arange(used.shape[0], dtype=jnp.int32) == choice)[:, None]
        return used + placed.astype(used.dtype) * req[None, :], choice

    used_final, choices = lax.scan(
        step, arr.node_used, (arr.pod_req, sf, pref, arr.pod_valid)
    )
    return choices, used_final


schedule_batch = partial(jax.jit, static_argnames=("cfg",))(schedule_batch_impl)
