"""Batched assignment with sequential-commit semantics — L3 (the hard part).

The reference schedules one pod per cycle; placing pod i mutates NodeInfo before
pod i+1 is considered (pkg/scheduler/schedule_one.go — ScheduleOne + the assume
cache, backend/cache/cache.go — AssumePod).  To reproduce those semantics in one
XLA program, everything capacity-independent (static feasibility, raw score
counts, selector matmuls) is evaluated for the whole batch up front as [P, N]
matrices, and a `lax.scan` over pods (in activeQ order == array order)
re-evaluates only the state-dependent terms per step:

  - NodeResourcesFit.Filter against the running node_used
  - NodePorts.Filter against the running ports_used
  - PodTopologySpread / InterPodAffinity against running PER-NODE count state
    cnt_node/anti_node/pref_node[T, N] (committed pods become "existing" for
    every later pod — including their own anti-affinity terms; see
    ops/pairwise.py for why the state is per-node rather than per-domain)
  - LeastAllocated / BalancedAllocation scores against used + this pod's request
  - per-pod NormalizeScore over the *currently* feasible set

Host selection is argmax of the weighted sum; ties break to the lowest node
index.  (The reference's selectHost — schedule_one.go — picks randomly among
equal-score nodes; this framework is deterministic by design, the "full-scoring
deterministic mode" deviation called out in SURVEY.md §7 hard part 1.  The
oracle applies the identical rule, so parity is exact within the framework.)

ONE implementation serves both execution modes: `axis_name=None` runs on a
single device; under shard_map (parallel/sharded.py) the same step function
sees local node shards and stitches global decisions with pmax/pmin/psum —
per-node score math never crosses shards, so both modes are bit-identical.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..api.snapshot import ClusterArrays
from . import filters, pairwise
from .scores import (
    MAX_NODE_SCORE,
    ScoreConfig,
    balanced_allocation,
    least_allocated,
    taint_prefer_counts,
)

_INT_MAX = jnp.iinfo(jnp.int32).max


def _rmax(x, axis_name):
    """Reduce-max over the node axis (last), then across shards if sharded."""
    m = jnp.max(x, axis=-1)
    return lax.pmax(m, axis_name) if axis_name else m


def _rmin(x, axis_name):
    m = jnp.min(x, axis=-1)
    return lax.pmin(m, axis_name) if axis_name else m


def _preferred_node_affinity_raw(arr: ClusterArrays, term_matches: jax.Array) -> jax.Array:
    """f32[P, N]: summed weights of matching preferred node-affinity terms
    (nodeaffinity/node_affinity.go — Score).  One [P, S] @ [S, N] matmul."""
    P, _ = arr.pod_pref_terms.shape
    S = term_matches.shape[0]
    ids = jnp.maximum(arr.pod_pref_terms, 0)
    w = jnp.where(arr.pod_pref_terms >= 0, arr.pod_pref_weights, 0.0)
    W = jnp.zeros((P, S), dtype=jnp.float32)
    W = W.at[jnp.arange(P)[:, None], ids].add(w)
    return W @ term_matches.astype(jnp.float32)


def schedule_scan(
    arr: ClusterArrays, cfg: ScoreConfig, axis_name: Optional[str] = None
) -> Tuple[jax.Array, jax.Array]:
    """The full scheduling step.  `arr` holds the whole cluster when
    axis_name is None, or this shard's node slice under shard_map.

    Returns (assignment i32[P] — GLOBAL node index or -1, node_used i32[N,R])."""
    local_n = arr.N
    if axis_name:
        base = lax.axis_index(axis_name).astype(jnp.int32) * local_n
    else:
        base = jnp.int32(0)
    my_nodes = base + jnp.arange(local_n, dtype=jnp.int32)

    tm = filters.term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)  # [S, Nl]
    nodesel = filters.node_selection_ok_from(tm, arr)  # [P, Nl]
    pin = arr.pod_nodename[:, None]
    nodename_ok = jnp.where(pin == -1, True, pin == my_nodes[None, :])
    sf = (
        arr.node_valid[None, :]
        & arr.pod_valid[:, None]
        & filters.taints_ok(arr)
        & nodesel
        & nodename_ok
    )
    n_alloc = arr.node_alloc
    # static per-term node->domain map + key presence, hoisted out of the scan
    # (ops/pairwise.py module docstring: per-node state layout).  D is a
    # static Python int — domain id D means "node lacks the key".
    D = arr.term_counts0.shape[1] - 1
    dom_by_term = arr.node_dom[arr.term_key]  # i32[T, Nl]
    has_key_all = dom_by_term < D  # bool[T, Nl]

    # Scan inputs assembled conditionally: disabled stages (cfg.enable_*) never
    # materialize their [P, N] matrices — a constant-per-pod score term cannot
    # change argmax, so pruning is decision-preserving.
    xs = {"req": arr.pod_req, "sf": sf, "valid": arr.pod_valid}
    if cfg.enable_taint_score:
        xs["pref"] = taint_prefer_counts(arr)  # [P, Nl]
    if cfg.enable_node_pref:
        xs["na"] = _preferred_node_affinity_raw(arr, tm)  # [P, Nl]
    if cfg.enable_pairwise:
        xs.update(
            nodesel=nodesel,
            aff=arr.pod_aff_terms,
            anti=arr.pod_anti_terms,
            spread_t=arr.pod_spread_terms,
            spread_skew=arr.pod_spread_maxskew,
            spread_hard=arr.pod_spread_hard,
            mt=arr.pod_match_terms,
            mv=arr.pod_match_vals,
            aself=arr.pod_aff_self,
        )
        if cfg.enable_interpod_score:
            xs["pref_t"] = arr.pod_pref_aff_terms
            xs["pref_w"] = arr.pod_pref_aff_w
    if cfg.enable_ports:
        xs["ports"] = arr.pod_ports
    if cfg.enable_image and arr.image_score.shape[1] == arr.N:
        xs["img"] = arr.image_score

    def norm_reverse(counts, feasible):
        mx = _rmax(jnp.where(feasible, counts, 0.0), axis_name)
        return jnp.where(mx > 0, MAX_NODE_SCORE - MAX_NODE_SCORE * counts / mx, MAX_NODE_SCORE)

    def step(state, xs):
        used, cnt_node, anti_node, pref_node, total_t, ports_used = state
        req, feas_row, valid = xs["req"], xs["sf"], xs["valid"]

        feasible = feas_row & filters.fit_ok(req, used, n_alloc)
        if cfg.enable_ports:
            feasible &= pairwise.ports_ok(ports_used, xs["ports"])
        if cfg.enable_pairwise:
            spread_ok, spread_raw = pairwise.spread_step(
                cnt_node, has_key_all, xs["spread_t"], xs["spread_skew"],
                xs["spread_hard"], xs["nodesel"] & arr.node_valid, axis_name,
            )
            feasible &= spread_ok & pairwise.interpod_required_ok(
                cnt_node, anti_node, total_t, has_key_all, xs["aff"], xs["anti"],
                xs["mt"], xs["mv"], xs["aself"],
            )
        requested = used + req[None, :]
        # score accumulation order mirrors the oracle exactly (float32 parity):
        # fit, balanced, taint, nodeAffinity, spread
        total = cfg.fit_weight * least_allocated(
            requested, n_alloc, cfg.score_resources
        ) + cfg.balanced_weight * balanced_allocation(
            requested, n_alloc, cfg.score_resources
        )
        if cfg.enable_taint_score:
            total = total + cfg.taint_weight * norm_reverse(xs["pref"], feasible)
        if cfg.enable_node_pref:
            # NodeAffinity preferred: DefaultNormalizeScore (not reversed)
            na_row = xs["na"]
            na_max = _rmax(jnp.where(feasible, na_row, 0.0), axis_name)
            total = total + cfg.node_affinity_weight * jnp.where(
                na_max > 0, na_row * MAX_NODE_SCORE / na_max, 0.0
            )
        if cfg.enable_pairwise:
            total = total + cfg.spread_weight * norm_reverse(spread_raw, feasible)
        if cfg.enable_pairwise and cfg.enable_interpod_score:
            # preferred inter-pod affinity: min/max normalization over feasible
            # (interpodaffinity/scoring.go — NormalizeScore)
            ip_raw = pairwise.interpod_pref_raw(
                cnt_node, pref_node, has_key_all, xs["pref_t"], xs["pref_w"],
                xs["mt"], xs["mv"],
            )
            mx = _rmax(jnp.where(feasible, ip_raw, -jnp.inf), axis_name)
            mn = -_rmax(jnp.where(feasible, -ip_raw, -jnp.inf), axis_name)
            ip_sc = jnp.where(
                mx > mn, MAX_NODE_SCORE * (ip_raw - mn) / (mx - mn), 0.0
            )
            total = total + cfg.interpod_weight * ip_sc
        if "img" in xs:  # ImageLocality: static, no per-pod normalization
            total = total + cfg.image_weight * xs["img"]
        total = jnp.where(feasible, total, -jnp.inf)
        best = _rmax(total, axis_name)
        schedulable = (best > -jnp.inf) & valid
        # lowest global index attaining the max
        cand = jnp.where((total == best) & feasible, my_nodes, _INT_MAX)
        choice = jnp.where(schedulable, _rmin(cand, axis_name).astype(jnp.int32), -1)

        placed = (my_nodes == choice)[:, None]
        used = used + placed.astype(used.dtype) * req[None, :]
        if cfg.enable_pairwise:
            # domain column of the chosen node, per term — owner shard broadcasts
            is_mine = (choice >= base) & (choice < base + local_n)
            local_col = jnp.clip(choice - base, 0, local_n - 1)
            dom_col = jnp.where(is_mine, dom_by_term[:, local_col], 0)
            if axis_name:
                dom_col = lax.psum(dom_col, axis_name)
            cnt_node, anti_node, total_t = pairwise.commit_counts(
                cnt_node, anti_node, total_t, dom_by_term, D,
                choice, dom_col, xs["mt"], xs["mv"], xs["anti"],
            )
            if cfg.enable_interpod_score:
                # the committed pod's own preferred terms join the symmetric
                # half for later pods
                bids = jnp.maximum(xs["pref_t"], 0)
                bw = jnp.where((xs["pref_t"] >= 0) & (choice >= 0), xs["pref_w"], 0.0)
                pref_node = pref_node.at[bids].add(
                    bw[:, None] * (dom_by_term[bids] == dom_col[bids][:, None])
                )
                if cfg.hard_pod_affinity_weight:
                    # ... and its REQUIRED affinity terms at hardPodAffinityWeight
                    # (interpodaffinity/scoring.go — processExistingPod)
                    aids = jnp.maximum(xs["aff"], 0)
                    aw = jnp.where(
                        (xs["aff"] >= 0) & (choice >= 0),
                        jnp.float32(cfg.hard_pod_affinity_weight),
                        0.0,
                    )
                    pref_node = pref_node.at[aids].add(
                        aw[:, None] * (dom_by_term[aids] == dom_col[aids][:, None])
                    )
        if cfg.enable_ports:
            ports_used = ports_used | (placed & xs["ports"][None, :])
        return (used, cnt_node, anti_node, pref_node, total_t, ports_used), choice

    # initial per-node state: ONE hoisted [T, N] gather each (cheap outside
    # the scan), bit-identical to reading the [T, D+1] tables per step
    cnt_node0 = jnp.take_along_axis(arr.term_counts0, dom_by_term, axis=1)
    anti_node0 = jnp.take_along_axis(arr.anti_counts0, dom_by_term, axis=1)
    pref_node0 = jnp.take_along_axis(arr.pref_own0, dom_by_term, axis=1)
    total_t0 = arr.term_counts0[:, :D].sum(axis=1)
    state0 = (
        arr.node_used, cnt_node0, anti_node0, pref_node0, total_t0,
        arr.node_ports0,
    )
    (used_final, _, _, _, _, _), choices = lax.scan(step, state0, xs)
    return choices, used_final


_CHUNK = 128  # pods per chunk on the chunked path (buckets are multiples)


def _chunkable(arr: ClusterArrays, cfg: ScoreConfig) -> bool:
    """The chunked scan applies when the ONLY scan-carried state is node
    usage: no pairwise/ports stages and no per-pod normalization stages
    (taint/nodeAffinity/image) — which is exactly the north-star
    heterogeneous shape and the basic/gang configs."""
    return (
        not cfg.enable_pairwise
        and not cfg.enable_ports
        and not cfg.enable_taint_score
        and not cfg.enable_node_pref
        and not (cfg.enable_image and arr.image_score.shape[1] == arr.N)
        and arr.P >= _CHUNK
        and arr.P % _CHUNK == 0
    )


def schedule_scan_chunked(arr: ClusterArrays, cfg: ScoreConfig) -> Tuple[jax.Array, jax.Array]:
    """Chunked sequential-commit scan, BIT-IDENTICAL to schedule_scan for
    fit+balanced-only configs (tests/test_assign_parity.py — chunked case).

    The per-pod scan pays ~10us/step of [N]-wide work at 20k nodes; here each
    CHUNK of pods hoists its dense candidate scores [C, N] against the
    chunk-start usage ONCE (MXU-friendly), and the inner commit scan touches
    only [C]-sized slot state: a pod's true score differs from the hoisted
    row exactly at nodes other chunk members committed to (at most C of
    them), so each step rewrites those few entries and re-argmaxes.  Exact
    because fit/least/balanced depend on per-node usage only — there are no
    cross-node normalizations on this path."""
    local_n = arr.N
    my_nodes = jnp.arange(local_n, dtype=jnp.int32)

    tm = filters.term_match(arr.sel_mask, arr.sel_kind, arr.node_labels)
    nodesel = filters.node_selection_ok_from(tm, arr)
    pin = arr.pod_nodename[:, None]
    nodename_ok = jnp.where(pin == -1, True, pin == my_nodes[None, :])
    sf = (
        arr.node_valid[None, :]
        & arr.pod_valid[:, None]
        & filters.taints_ok(arr)
        & nodesel
        & nodename_ok
    )
    n_alloc = arr.node_alloc
    P, N, R = arr.P, arr.N, arr.R
    C = _CHUNK
    res = cfg.score_resources
    neg_inf = -jnp.inf

    reqs = arr.pod_req.reshape(P // C, C, R)
    sfs = sf.reshape(P // C, C, N)
    valids = arr.pod_valid.reshape(P // C, C)

    def chunk(used0, xs):
        creq, csf, cvalid = xs
        # hoisted dense scores vs chunk-start usage (vmap = the per-step ops
        # batched, so float32 results are bit-identical to the plain scan)
        requested = used0[None, :, :] + creq[:, None, :]  # [C, N, R]
        fit0 = jax.vmap(filters.fit_ok, (0, None, None))(creq, used0, n_alloc)
        total0 = cfg.fit_weight * jax.vmap(
            least_allocated, (0, None, None)
        )(requested, n_alloc, res) + cfg.balanced_weight * jax.vmap(
            balanced_allocation, (0, None, None)
        )(requested, n_alloc, res)
        total0 = jnp.where(csf & fit0, total0, neg_inf)  # [C, N]

        def step(st, xs2):
            tids, tused, talloc = st  # [C], [C, R], [C, R]
            req_i, row0, sf_row, valid_i, slot_i = xs2
            live = tids >= 0
            # corrected score at touched nodes (same formulas on [C, R] rows)
            requested_t = tused + req_i[None, :]
            fit_t = jnp.all(
                (req_i[None, :] == 0) | (req_i[None, :] <= talloc - tused), axis=1
            )
            sc_t = cfg.fit_weight * least_allocated(
                requested_t, talloc, res
            ) + cfg.balanced_weight * balanced_allocation(requested_t, talloc, res)
            ok_t = live & fit_t & sf_row[jnp.maximum(tids, 0)]
            val_t = jnp.where(ok_t, sc_t, neg_inf)
            # overwrite the touched entries of the hoisted row (dead slots
            # scatter out of bounds and are dropped)
            row = row0.at[jnp.where(live, tids, N)].set(val_t, mode="drop")
            best = row.max()
            cand = jnp.where(row == best, my_nodes, _INT_MAX)
            schedulable = (best > neg_inf) & valid_i
            choice = jnp.where(schedulable, cand.min().astype(jnp.int32), -1)
            # commit: add to the existing slot, or open THIS step's own slot
            exists = live & (tids == choice)
            placed = choice >= 0
            tused = tused + (exists & placed)[:, None] * req_i[None, :]
            new_here = placed & ~exists.any()
            mine = (jnp.arange(C, dtype=jnp.int32) == slot_i) & new_here
            cc = jnp.maximum(choice, 0)
            tids = jnp.where(mine, choice, tids)
            tused = jnp.where(mine[:, None], (used0[cc] + req_i)[None, :], tused)
            talloc = jnp.where(mine[:, None], n_alloc[cc][None, :], talloc)
            return (tids, tused, talloc), choice

        st0 = (
            jnp.full(C, -1, dtype=jnp.int32),
            jnp.zeros((C, R), dtype=used0.dtype),
            jnp.ones((C, R), dtype=used0.dtype),
        )
        xs2 = (creq, total0, csf, cvalid, jnp.arange(C, dtype=jnp.int32))
        _, choices_c = lax.scan(step, st0, xs2)
        placed = (choices_c >= 0)[:, None]
        used0 = used0.at[jnp.maximum(choices_c, 0)].add(
            placed * creq, mode="drop"
        )
        return used0, choices_c

    used_final, choices = lax.scan(chunk, arr.node_used, (reqs, sfs, valids))
    return choices.reshape(P), used_final


def schedule_batch_impl(arr: ClusterArrays, cfg: ScoreConfig) -> Tuple[jax.Array, jax.Array]:
    if _chunkable(arr, cfg):
        return schedule_scan_chunked(arr, cfg)
    return schedule_scan(arr, cfg, axis_name=None)


schedule_batch = partial(jax.jit, static_argnames=("cfg",))(schedule_batch_impl)
