"""Persisted per-platform autotune winners — the knob half of
`bench/autotune.py`.

The chunked-kernel shape knobs (KTPU_INC_CHUNK and the commit-wave family
KTPU_WAVE_K / KTPU_WAVE_BLOCK / KTPU_WAVE_ITERS) are TRACE-TIME constants:
they are read once at `ops.assign` import and baked into every jit trace,
which is why sweeps run each candidate in a fresh subprocess
(bench/autotune.py, same discipline as bench/rounds_proof.py's
KTPU_REPAIR_ITERS sweep).  None of them change DECISIONS — chunk size and
wave shape move only commit ordinals and wall time (PARITY.md), so a tuned
winner is a pure performance choice and safe to persist.

Resolution order for every tuned knob (ops/assign.py — `tuned_knob`):

  1. the explicit env var (operator override, always wins)
  2. the persisted per-platform winner file, when one exists
  3. the shipped default

The winner file lives NEXT TO the persistent compilation cache
(KTPU_TUNING_DIR, defaulting to KTPU_COMPILE_CACHE_DIR) as
``ktpu-tuned-<platform>.json`` — the same "per-box self-serve state"
location: a box that persists compiled programs also remembers which knob
shape those programs should be compiled with.  When neither dir is set the
lookup is a no-op and the shipped defaults apply; importing this module
never initializes a JAX backend in that case (the platform name is only
resolved once a directory is configured).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

# knobs the autotuner may persist; anything else in a winner file is
# ignored on load (fail-open: a stale file from a future/past version
# can never inject an unknown trace-time constant)
TUNABLE_KNOBS = (
    "KTPU_INC_CHUNK", "KTPU_WAVE_K", "KTPU_WAVE_BLOCK", "KTPU_WAVE_ITERS",
    "KTPU_PACK_MASKS", "KTPU_SCORE_DTYPE", "KTPU_MESH_PODS",
)

# per-knob value type: every knob is an int unless listed here
# (KTPU_SCORE_DTYPE is a dtype name — "bf16" | "f32")
_KNOB_TYPES = {"KTPU_SCORE_DTYPE": str}


def _coerce(name: str, v: Any):
    return _KNOB_TYPES.get(name, int)(v)


def tuning_dir() -> Optional[str]:
    """KTPU_TUNING_DIR, falling back to KTPU_COMPILE_CACHE_DIR (the winner
    file sits next to the compile cache); None disables persistence."""
    return (
        os.environ.get("KTPU_TUNING_DIR")
        or os.environ.get("KTPU_COMPILE_CACHE_DIR")
        or None
    )


def _platform(platform: Optional[str] = None) -> str:
    if platform:
        return platform
    import jax

    return jax.default_backend()


def tuning_path(platform: Optional[str] = None) -> Optional[str]:
    """Path of the per-platform winner file, or None when no tuning/compile
    cache dir is configured."""
    root = tuning_dir()
    if not root:
        return None
    return os.path.join(root, f"ktpu-tuned-{_platform(platform)}.json")


def load_tuned(platform: Optional[str] = None) -> Dict[str, Any]:
    """The persisted winner's knob dict (TUNABLE_KNOBS subset), or {} when
    no winner exists.  Fail-open on any read/parse error: autotune state
    must never be able to break scheduling."""
    path = tuning_path(platform)
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
        knobs = doc.get("knobs", {})
        return {k: _coerce(k, v) for k, v in knobs.items()
                if k in TUNABLE_KNOBS}
    except (OSError, ValueError, TypeError):
        return {}


def save_tuned(
    knobs: Dict[str, int], score: Dict[str, Any],
    platform: Optional[str] = None,
) -> Optional[str]:
    """Persist the winning knob dict + its scorecard (measured seconds and
    the analytic-ledger shares that justified it) for `platform`.  Returns
    the written path, or None when no tuning dir is configured."""
    path = tuning_path(platform)
    if not path:
        return None
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {
        "knobs": {k: _coerce(k, v) for k, v in knobs.items()
                  if k in TUNABLE_KNOBS},
        "score": score,
        "platform": _platform(platform),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)  # atomic: concurrent readers see old or new
    return path


def tuned_knob(name: str, default):
    """Trace-time knob resolution: env var > persisted winner > default.
    Called at `ops.assign` / `ops.bitplane` IMPORT time — the resolved value
    is baked into every jit trace, exactly like the plain
    int(os.environ.get(...)) pattern it extends.  Value type follows the
    knob (_KNOB_TYPES): ints except KTPU_SCORE_DTYPE (a dtype name)."""
    raw = os.environ.get(name, "")
    if raw:
        return _coerce(name, raw)
    tuned = load_tuned()
    if name in tuned:
        return _coerce(name, tuned[name])
    return default
