"""Named sub-phase scopes — the kernel-interior attribution vocabulary.

PR 6's attribution engine stops at `device_kernel`; these scopes extend the
per-phase metering BELOW the jit boundary (PAPER.md's
framework_extension_point_duration_seconds culture, applied to the compiled
program itself).  Every production kernel (ops/assign.py, ops/incremental.py,
ops/gang.py, parallel/ring.py) annotates its regions with `jax.named_scope`
from the ONE declared vocabulary below; the scope names survive lowering as
HLO `op_name` metadata, which is what joins the two observability halves:

  measured  bench/profiling.py maps jax.profiler device-trace ops back to
            their owning sub-phase (innermost declared scope wins) and emits
            the self-time table `bench.harness --profile` extends
            scheduler/attribution.py with, below `device_kernel`
  analytic  analysis/costmodel.py walks the traced jaxprs and charges every
            leaf eqn's FLOPs/HBM bytes to the same owning sub-phase — the
            roofline ledger KTPU019 reconciles against the measured table

SUBPHASES is deliberately closed: a kernel region outside every declared
scope is an attribution hole (KTPU019 flags heavy unowned eqns, fail-closed
like KTPU013), and a new name here must land in both halves at once —
costmodel and profiling import the tuple from this module so the three can
never drift onto different vocabularies.

KTPU_NAMED_SCOPES=0 turns every scope into a no-op at TRACE time (the
parity escape hatch: tests/test_costmodel.py proves annotation changes zero
placements and zero TRACE_COUNTS across every route x donation variant by
comparing the two settings).
"""

from __future__ import annotations

import contextlib
import os

import jax

# the declared kernel-interior sub-phases, in canonical report order:
#   hoist       per-chunk / per-cycle score-matrix builds ([C, Nl] dense,
#               [U1, N] class hoists, static-feasibility preludes)
#   score       top-k candidate extraction + per-round exact re-hoists
#   normalize   per-pod NormalizeScore scalar stitches (rounds kernel)
#   round_loop  the prefix-commit while_loop itself — loop plumbing and any
#               interior work not owned by a finer scope (the O(C^2K)
#               ROADMAP-1 target)
#   speculate   pass-1 dispersal speculation (rank seeding, pointer jumps)
#   repair      pass-2 exact revalidation under the intra-round prefix
#   commit      prefix commit + usage/count-state absorption + column patch
#   commit_batch  the class-batched commit-wave stage (ops/assign.py —
#               _wave_commit_stage): epoch top-k refresh, block pointer
#               walk, certification scan and wave commits.  A SIBLING of
#               round_loop, not part of its rollup — the wave replaces the
#               prefix-commit loop's work, so lumping it under round_loop
#               would hide exactly the collapse `round_loop_fraction`
#               exists to measure
SUBPHASES = (
    "hoist", "score", "normalize", "round_loop", "speculate", "repair",
    "commit", "commit_batch",
)


def scopes_enabled() -> bool:
    """KTPU_NAMED_SCOPES=0 disables sub-phase annotation (read at TRACE
    time: flipping it after a shape/cfg is jit-cached has no effect on that
    cache entry — parity tests clear the jit caches between settings)."""
    return os.environ.get("KTPU_NAMED_SCOPES", "") != "0"


def subphase(name: str):
    """`jax.named_scope(name)` for a DECLARED sub-phase (or a no-op under
    KTPU_NAMED_SCOPES=0).  Undeclared names raise at trace time: the scope
    vocabulary is the contract both observatory halves key on."""
    if name not in SUBPHASES:
        raise ValueError(
            f"undeclared kernel sub-phase {name!r} (declared: {SUBPHASES})"
        )
    if not scopes_enabled():
        return contextlib.nullcontext()
    return jax.named_scope(name)


def subphase_of(path: str) -> str:
    """The owning sub-phase of an HLO op_name / jaxpr name-stack path — the
    INNERMOST declared scope component ('' when none owns it).  One
    definition shared by the measured (bench/profiling.py) and analytic
    (analysis/costmodel.py) halves, so an op can never be owned by two
    different sub-phases across the two ledgers."""
    for comp in reversed(path.split("/")):
        if comp in SUBPHASES:
            return comp
    return ""
