"""Feasibility (Filter extension point) as batched array ops — L1.

One jitted evaluation replaces the reference's chunked 16-goroutine fan-out of
per-node Filter plugins (pkg/scheduler/framework/parallelize/parallelism.go —
Parallelizer.Until; pkg/scheduler/schedule_one.go — findNodesThatFitPod).

The capacity check (NodeResourcesFit.Filter — noderesources/fit.go) is split out
as `fit_ok`: it depends on node_used, which mutates as the commit scan places
pods (ops/assign.py), so it re-evaluates in-scan while everything
capacity-independent is computed once here for the whole batch:

  TaintToleration.Filter   (tainttoleration/taint_toleration.go)  -> taint test
  NodeAffinity.Filter + spec.nodeSelector (nodeaffinity/node_affinity.go)
                                                                 -> term matmul
  NodeUnschedulable.Filter (via the synthetic unschedulable taint, api/snapshot.py)
  NodeName.Filter          (nodename/node_name.go)               -> index equality
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..api import vocab as v
from ..api.snapshot import ClusterArrays


def term_match(sel_mask: jax.Array, sel_kind: jax.Array, node_labels: jax.Array) -> jax.Array:
    """[S, E, L] masks x [N, L] labels -> bool[S, N]: which nodes satisfy each
    interned selector term.

    The AnyOf/NoneOf primitives (api/vocab.py) become one counting matmul on the
    MXU; counts are exact in f32 (< 2^24 literals).
    """
    counts = jnp.einsum(
        "sel,nl->sen", sel_mask, node_labels, precision=jax.lax.Precision.HIGHEST
    )
    kind = sel_kind[:, :, None]
    ok = jnp.where(
        kind == v.KIND_ANY,
        counts > 0,
        jnp.where(kind == v.KIND_NONE, counts == 0, kind == v.KIND_PAD),
    )
    return jnp.all(ok, axis=1)


def node_selection_ok_from(tm: jax.Array, arr: ClusterArrays) -> jax.Array:
    """bool[P, N] from a precomputed term_match matrix (shared with preferred
    node-affinity scoring)."""
    ids = jnp.maximum(arr.pod_terms, 0)  # [P, TT]
    per_term = tm[ids] & (arr.pod_terms >= 0)[:, :, None]  # [P, TT, N]
    return jnp.where(arr.pod_has_sel[:, None], per_term.any(axis=1), True)


def node_selection_ok(arr: ClusterArrays) -> jax.Array:
    """bool[P, N]: spec.nodeSelector AND required node affinity (ORed terms)."""
    return node_selection_ok_from(
        term_match(arr.sel_mask, arr.sel_kind, arr.node_labels), arr
    )


def taints_ok(arr: ClusterArrays) -> jax.Array:
    """bool[P, N]: every hard (NoSchedule/NoExecute) taint on the node is
    tolerated.  Counting matmul over the taint vocab."""
    intolerable = jnp.einsum(
        "pt,nt->pn",
        (~arr.pod_tol_ns).astype(jnp.float32),
        arr.node_taint_ns.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return intolerable == 0


def nodename_ok(arr: ClusterArrays) -> jax.Array:
    """bool[P, N]: spec.nodeName pinning (-1 unset, -2 named node missing)."""
    n_idx = jnp.arange(arr.N, dtype=jnp.int32)[None, :]
    pin = arr.pod_nodename[:, None]
    return jnp.where(pin == -1, True, pin == n_idx)


def static_feasible(arr: ClusterArrays) -> jax.Array:
    """bool[P, N]: all capacity-independent filters, one batched evaluation."""
    return (
        arr.node_valid[None, :]
        & arr.pod_valid[:, None]
        & taints_ok(arr)
        & node_selection_ok(arr)
        & nodename_ok(arr)
    )


def static_feasible_rows(
    tm: jax.Array, node_valid: jax.Array, node_taint_ns: jax.Array,
    my_nodes: jax.Array, pod_terms: jax.Array, pod_has_sel: jax.Array,
    pod_tol_ns: jax.Array, pod_nodename: jax.Array, pod_valid: jax.Array,
):
    """(sf [B, Nl], nodesel [B, Nl]) for a pod ROW BLOCK against the node
    slice `my_nodes` (global ids — the sharded kernels' base + arange).

    The block form exists for the packed data plane (ops/bitplane.py): the
    chunked/rounds kernels map it over C-row blocks and pack each block's
    result, so the widest dense mask transient is [C, Nl], never [P, Nl] —
    the resident plane rides as uint32 words.  Same ops, same order as
    static_feasible, so the bits are identical to the dense hoist."""
    ids = jnp.maximum(pod_terms, 0)
    per_term = tm[ids] & (pod_terms >= 0)[:, :, None]
    nodesel = jnp.where(pod_has_sel[:, None], per_term.any(axis=1), True)
    intolerable = jnp.einsum(
        "pt,nt->pn",
        (~pod_tol_ns).astype(jnp.float32),
        node_taint_ns.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    pin = pod_nodename[:, None]
    nn_ok = jnp.where(pin == -1, True, pin == my_nodes[None, :])
    sf = (
        node_valid[None, :]
        & pod_valid[:, None]
        & (intolerable == 0)
        & nodesel
        & nn_ok
    )
    return sf, nodesel


def fit_ok(pod_req: jax.Array, node_used: jax.Array, node_alloc: jax.Array) -> jax.Array:
    """bool[N] for one pod: used + req <= alloc on every resource (int32 exact).

    reference: noderesources/fit.go — fitsRequest.  Called inside the commit
    scan with the running `node_used` state.

    Computed as req <= alloc - used, NOT used + req <= alloc: the subtraction
    form cannot overflow int32 (alloc and used are both >= 0), whereas the sum
    wraps negative for near-int32-max quantities and would falsely pass.
    Resources the pod does not request (req == 0) never block — the reference
    skips them, so a node overcommitted on memory still accepts a 0-memory pod.
    """
    req = pod_req[None, :]
    return jnp.all((req == 0) | (req <= node_alloc - node_used), axis=1)
