from .filters import static_feasible, term_match  # noqa: F401
from .scores import ScoreConfig, DEFAULT_SCORE_CONFIG  # noqa: F401
from .assign import schedule_batch  # noqa: F401
