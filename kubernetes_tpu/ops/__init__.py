from .filters import static_feasible, term_match  # noqa: F401
from .scores import ScoreConfig, DEFAULT_SCORE_CONFIG, infer_score_config  # noqa: F401
from .assign import schedule_batch, schedule_batch_ordinals  # noqa: F401
