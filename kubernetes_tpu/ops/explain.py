"""Unschedulable diagnosis kernel — the explainability plane (ISSUE 13).

The reference scheduler's signature observability surface is the per-pod
`Diagnosis` built by schedule_one.go (NodeToStatusMap: one failing plugin
status per node) rendered by fitError.Error() into the message every operator
greps for: "0/5000 nodes are available: 2000 Insufficient cpu, 1500 node(s)
had untolerated taint.".  The device batch path fuses all filters into one
eligibility mask (ops/filters.py — static_feasible & fit_ok), so the verdict
`-1` carries no reason — this module re-derives the reasons ON DEMAND, for
the FAILED equivalence classes only (U_f ≪ P), strictly off the warm step:

  one jitted O(U_f·N) evaluation -> i32[U_f, NUM_REASONS] per-class
  {reason -> node count} vectors -> decoded through the class index back to
  per-pod upstream-shaped messages + pod_unschedulable_reasons_total{reason}.

Reason attribution rule (shared bit-for-bit by the kernel and the host
oracle `explain_oracle`; PARITY.md "Explainability"): every VALID node is
claimed by exactly ONE reason, the first failing filter in the reference's
plugin order —

  NodeName > NodeUnschedulable > TaintToleration > NodeAffinity >
  NodeResourcesFit (first insufficient resource in meta.resources order) >
  residual ("otherwise feasible": nodes that pass every capacity-independent
  filter and fit at the supplied usage — blocked in-scan by commit-state
  terms the fused kernels fold in: pod affinity/spread/ports, capacity
  races, speculation repair, or gang-quorum revocation)

so per-class counts always sum to the valid-node count — an exactly
checkable invariant, unlike upstream's multi-reason statuses (deviation
documented in PARITY.md).  Counts are computed against the CALLER-SUPPLIED
node usage (the scheduler passes post-cycle usage: what the operator sees
and the retry will face).

KTPU_EXPLAIN=1 gates the whole plane (KTPU005 cheap-gate pattern: one env
read per failing cycle, zero work otherwise); the kernel is additive-only —
it never touches the twelve production routes (KTPU010/KTPU011 stay clean
with it enabled).
"""

from __future__ import annotations

import os
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .filters import term_match

# fixed structural reason codes (kernel column order; fit columns follow at
# FIT_BASE..FIT_BASE+R-1, the residual "otherwise feasible" column is last)
R_NODENAME = 0
R_UNSCHED = 1
R_TAINT = 2
R_AFFINITY = 3
FIT_BASE = 4

LBL_NODENAME = "node(s) didn't match the requested node name"
LBL_UNSCHED = "node(s) were unschedulable"
LBL_TAINT = "node(s) had untolerated taint"
LBL_AFFINITY = "node(s) didn't match Pod's node affinity/selector"
LBL_FEASIBLE = "node(s) were otherwise feasible (blocked in-scan: capacity race, pod affinity/spread/ports, or gang quorum)"


def explain_enabled() -> bool:
    """KTPU_EXPLAIN=1 arms the diagnosis plane (default off: the device
    failure path records reason-free events exactly as before)."""
    return os.environ.get("KTPU_EXPLAIN", "") == "1"


def n_reasons(n_resources: int) -> int:
    return FIT_BASE + n_resources + 1


def reason_labels(resources: Sequence[str]) -> List[str]:
    """Column index -> upstream-shaped reason label (fitError vocabulary)."""
    return (
        [LBL_NODENAME, LBL_UNSCHED, LBL_TAINT, LBL_AFFINITY]
        + [f"Insufficient {r}" for r in resources]
        + [LBL_FEASIBLE]
    )


@jax.jit
def _explain_kernel(
    node_valid, node_alloc, node_used, node_unsched, node_labels,
    node_taint_ns, sel_mask, sel_kind,
    rep_valid, rep_req, rep_tol_ns, rep_nodename, rep_terms, rep_has_sel,
):
    """i32[F, 4+R+1] one-reason-per-node counts for F class representatives.

    Pure re-expression of ops/filters.py's primitives as per-filter masks:
    the SAME counting matmuls (exact in f32, < 2^24 literals), the SAME
    subtraction-form fit test — only un-fused, so each node's first failing
    filter is observable.  O(F·N) elementwise + two [F,T/S]-sized matmuls;
    never on the warm step."""
    N = node_valid.shape[0]
    R = rep_req.shape[1]
    valid = node_valid[None, :]  # [1, N] broadcasts over F

    # NodeName.Filter (filters.nodename_ok, negated)
    n_idx = jnp.arange(N, dtype=jnp.int32)[None, :]
    pin = rep_nodename[:, None]
    name_bad = jnp.where(pin == -1, False, pin != n_idx)  # [F, N]

    # TaintToleration.Filter (filters.taints_ok): the synthetic
    # node.kubernetes.io/unschedulable taint (api/snapshot.py) is in
    # node_taint_ns too, so an intolerable taint on an unschedulable node
    # is claimed by NodeUnschedulable first — the reference's plugin order.
    intolerable = jnp.einsum(
        "ft,nt->fn",
        (~rep_tol_ns).astype(jnp.float32),
        node_taint_ns.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ) > 0  # [F, N]
    unsched_bad = intolerable & node_unsched[None, :]
    taint_bad = intolerable & ~node_unsched[None, :]

    # NodeAffinity.Filter + spec.nodeSelector (filters.node_selection_ok)
    tm = term_match(sel_mask, sel_kind, node_labels)  # [S, N]
    ids = jnp.maximum(rep_terms, 0)  # [F, TT]
    per_term = tm[ids] & (rep_terms >= 0)[:, :, None]  # [F, TT, N]
    aff_bad = jnp.where(rep_has_sel[:, None], ~per_term.any(axis=1), False)

    # NodeResourcesFit at the supplied usage (filters.fit_ok's overflow-safe
    # subtraction form; req == 0 never blocks)
    free = node_alloc[None, :, :] - node_used[None, :, :]  # [1, N, R]
    req = rep_req[:, None, :]  # [F, 1, R]
    short = (req != 0) & (req > free)  # [F, N, R]
    fit_bad = short.any(axis=2)

    # priority claim: first failing filter owns the node
    claimed = jnp.zeros_like(name_bad)
    cols = []
    for mask in (name_bad, unsched_bad, taint_bad, aff_bad):
        claim = mask & ~claimed & valid
        cols.append(claim.sum(axis=1, dtype=jnp.int32))
        claimed = claimed | claim
    fit_claim = fit_bad & ~claimed & valid  # [F, N]
    first_r = jnp.argmax(short, axis=2)  # first insufficient resource
    onehot = (
        (jnp.arange(R, dtype=first_r.dtype)[None, None, :] == first_r[:, :, None])
        & fit_claim[:, :, None]
    )
    fit_counts = onehot.sum(axis=1, dtype=jnp.int32)  # [F, R]
    claimed = claimed | fit_claim
    feasible = (valid & ~claimed).sum(axis=1, dtype=jnp.int32)

    out = jnp.concatenate(
        [jnp.stack(cols, axis=1), fit_counts, feasible[:, None]], axis=1
    )
    return jnp.where(rep_valid[:, None], out, 0)


def _pad_pow2(n: int, minimum: int = 4) -> int:
    p = minimum
    while p < n:
        p *= 2
    return p


def explain_classes(
    arr, reps: np.ndarray, node_used: Optional[np.ndarray] = None
) -> np.ndarray:
    """Per-class reason-count vectors: i64[F, 4+R+1] for the class
    representatives `reps` (device pod row indices).  `node_used` defaults to
    the encoded cycle-start usage; the scheduler passes post-cycle usage.

    The rep rows are gathered on host (F is tiny — failed classes only) so
    the jit signature is [F_pad, ·]: F_pad is the next power of two (min 4),
    keeping retraces bounded by log2(U) per cluster shape, never per cycle.
    """
    reps = np.asarray(reps, dtype=np.int64)
    k = n_reasons(arr.pod_req.shape[1])
    if reps.size == 0:
        return np.zeros((0, k), dtype=np.int64)
    used = arr.node_used if node_used is None else node_used
    f_pad = _pad_pow2(int(reps.size))
    pad_reps = np.zeros(f_pad, dtype=np.int64)
    pad_reps[: reps.size] = reps
    rep_valid = np.zeros(f_pad, dtype=bool)
    rep_valid[: reps.size] = True
    counts = _explain_kernel(
        np.asarray(arr.node_valid), np.asarray(arr.node_alloc),
        np.asarray(used), np.asarray(arr.node_unsched),
        np.asarray(arr.node_labels), np.asarray(arr.node_taint_ns),
        np.asarray(arr.sel_mask), np.asarray(arr.sel_kind),
        rep_valid,
        np.asarray(arr.pod_req)[pad_reps],
        np.asarray(arr.pod_tol_ns)[pad_reps],
        np.asarray(arr.pod_nodename)[pad_reps],
        np.asarray(arr.pod_terms)[pad_reps],
        np.asarray(arr.pod_has_sel)[pad_reps],
    )
    return np.asarray(counts)[: reps.size].astype(np.int64)


def explain_oracle(
    arr, reps: Sequence[int], node_used: Optional[np.ndarray] = None
) -> np.ndarray:
    """Independent host recount of explain_classes — per-node python/numpy
    evaluation of the same attribution rule (parity IS the feature: the
    kernel's counts must equal this exactly, tests/test_explain.py)."""
    used = np.asarray(arr.node_used if node_used is None else node_used,
                      dtype=np.int64)
    alloc = np.asarray(arr.node_alloc, dtype=np.int64)
    R = alloc.shape[1]
    k = n_reasons(R)
    out = np.zeros((len(reps), k), dtype=np.int64)
    node_valid = np.asarray(arr.node_valid)
    sel_mask = np.asarray(arr.sel_mask)  # [S, E, L]
    sel_kind = np.asarray(arr.sel_kind)  # [S, E]
    node_labels = np.asarray(arr.node_labels)  # [N, L]
    from ..api import vocab as v

    # per-term node-satisfaction matrix, integer-exact matmul (the kernel
    # uses the f32 MXU path; both are exact below 2^24 literals)
    cnt = np.einsum("sel,nl->sen", sel_mask.astype(np.int64),
                    node_labels.astype(np.int64))
    ok_e = np.where(
        sel_kind[:, :, None] == v.KIND_ANY, cnt > 0,
        np.where(sel_kind[:, :, None] == v.KIND_NONE, cnt == 0,
                 sel_kind[:, :, None] == v.KIND_PAD),
    )
    tm = ok_e.all(axis=1)  # [S, N]
    for f, p in enumerate(reps):
        p = int(p)
        req = np.asarray(arr.pod_req[p], dtype=np.int64)
        tol = np.asarray(arr.pod_tol_ns[p])
        pin = int(arr.pod_nodename[p])
        terms = [int(s) for s in arr.pod_terms[p] if s >= 0]
        has_sel = bool(arr.pod_has_sel[p])
        for n in range(alloc.shape[0]):
            if not node_valid[n]:
                continue
            if pin != -1 and pin != n:
                out[f, R_NODENAME] += 1
                continue
            intol = bool(np.any(arr.node_taint_ns[n] & ~tol))
            if intol and bool(arr.node_unsched[n]):
                out[f, R_UNSCHED] += 1
                continue
            if intol:
                out[f, R_TAINT] += 1
                continue
            if has_sel and not any(tm[s, n] for s in terms):
                out[f, R_AFFINITY] += 1
                continue
            short = [j for j in range(R)
                     if req[j] != 0 and req[j] > alloc[n, j] - used[n, j]]
            if short:
                out[f, FIT_BASE + short[0]] += 1
                continue
            out[f, k - 1] += 1
    return out


def render_unschedulable(n_nodes: int, counts: Mapping[str, int]) -> str:
    """The fitError.Error() analog, shared by the device diagnosis AND the
    CPU path's per-plugin statuses: "0/N nodes are available: c1 reason1,
    c2 reason2." — reasons ordered by descending count then label (a
    deterministic rendering of upstream's sorted reason histogram)."""
    present = sorted(
        ((int(c), lbl) for lbl, c in counts.items() if c > 0),
        key=lambda cl: (-cl[0], cl[1]),
    )
    head = f"0/{n_nodes} nodes are available"
    if not present:
        return head + "."
    return head + ": " + ", ".join(f"{c} {lbl}" for c, lbl in present) + "."


def dominant_reason(counts: Mapping[str, int]) -> str:
    """The single reason label claiming the most nodes — the label
    pod_unschedulable_reasons_total{reason} aggregates under.  Ties break
    to the EARLIER entry in the mapping's insertion order, so callers must
    pass a deterministically ordered mapping: the device decode passes
    filter-priority column order; the CPU path passes label-sorted counts
    (its accumulation order follows the rotating node cursor)."""
    best, best_c = "", -1
    for lbl, c in counts.items():
        if int(c) > best_c:
            best, best_c = lbl, int(c)
    return best


def diagnose_failed(
    arr, meta, failed_rows: Sequence[int],
    node_used: Optional[np.ndarray] = None,
) -> Tuple[Dict[int, str], Dict[int, str], List[dict]]:
    """The decode half: group failed device rows by equivalence class
    (api/delta.class_groups — all pods of one class share spec, hence share
    the diagnosis), run ONE kernel evaluation over the class reps, and map
    the per-class vectors back to per-row messages.

    Returns (row -> message, row -> dominant reason label, per-class flight
    records [{rep_row, pods, counts}]).
    """
    from ..api.delta import class_groups

    reps, group_of = class_groups(meta, failed_rows)
    if reps.size == 0:
        return {}, {}, []
    counts = explain_classes(arr, reps, node_used)
    labels = reason_labels(meta.resources)
    per_class_counts: List[Dict[str, int]] = []
    class_msgs: List[str] = []
    class_dom: List[str] = []
    for g in range(reps.size):
        cc = {labels[j]: int(counts[g, j]) for j in range(len(labels))
              if counts[g, j] > 0}
        per_class_counts.append(cc)
        class_msgs.append(render_unschedulable(meta.n_nodes, cc))
        class_dom.append(dominant_reason(cc))
    messages = {int(r): class_msgs[g] for r, g in group_of.items()}
    dominant = {int(r): class_dom[g] for r, g in group_of.items()}
    pods_per_class = [0] * reps.size
    for g in group_of.values():
        pods_per_class[g] += 1
    records = [
        {"rep_row": int(reps[g]), "pods": pods_per_class[g],
         "counts": per_class_counts[g]}
        for g in range(reps.size)
    ]
    return messages, dominant, records
