"""Incremental warm-cycle hoisting — equivalence classes + dirty-node rescoring.

The reference never re-derives the world from scratch: the watch cache
re-snapshots in O(changes) (storage/cacher/cacher.go — type Cacher,
pkg/scheduler/backend/cache — UpdateSnapshot) and the historical equivalence
cache scored one pod per identical *spec*, not one per replica.  The host
side of this repo already works that way (api/delta.py); this module makes
the DEVICE step incremental too, with two stacked levers:

1. EQUIVALENCE CLASSES.  Every pod-side array is built per unique spec and
   scattered through the class-index vector (api/delta.py — _pod_side), so
   rows within a class are bit-identical by construction.  The expensive
   [P, N] hoists — static feasibility, the fit+balanced base scores, the
   usage-independent raw score matrices — therefore collapse to [U, N]
   class matrices (U = unique specs, U ≪ P for template-stamped waves) that
   the kernels gather back per pod through `IncState.cls`
   (ops/assign.py — schedule_scan_chunked / schedule_scan_rounds, inc=).

2. DIRTY-NODE RESCORING.  The class hoist splits into a usage-INDEPENDENT
   static side (feasibility masks, taint/node-affinity raws — stable across
   warm cycles while node labels/taints and the wave's class set hold) and
   a usage-DEPENDENT side (fit + balanced base scores + fit mask).  Both
   stay RESIDENT on device across cycles (placed per the partition rule
   table under a mesh, like the DeltaEncoder's buffers).  On a warm cycle
   only the
   columns of nodes whose usage changed since the previous encode — the
   dirty set, diffed against the encoder's previous node_used and
   cross-checked with the dirty-node set api/delta.py tracks — are
   recomputed and scattered into the resident cache.  An explicit
   invalidation fingerprint (host-array identity over every input the
   cached matrices read, mirroring ClusterSide's wave-fingerprint
   discipline) forces a full re-hoist on any mismatch.

Exactness: every patched column is recomputed with the *same* vmapped
formulas the kernels' dense hoists apply (fit_ok / fit_score /
balanced_allocation are per-(class, node) elementwise), so a patched cache
is bit-identical to a from-scratch hoist of the same cluster state, and
kernel decisions are bit-identical to the serial oracle
(tests/test_incremental.py pins the full matrix).

The same resident [U, N] matrices are the substrate of the class-batched
commit waves (ops/assign.py — _wave_commit_stage, ISSUE 17): the wave's
per-class top-k candidate lists are `lax.top_k` over exactly these rows, so
a patched cache that is bit-identical to the dense hoist makes the wave's
commits bit-identical to the serial round loop too — the parity guarantee
above and the wave invariants (PARITY.md — "Class-batched commit-wave
invariants") are one argument, not two.

DONATION-ALIASING RULE (PARITY.md): the resident cache buffers are passed
to the step as a SEPARATE, never-donated argument — a donated kernel only
ever consumes the per-wave `ClusterArrays` transfers.  The cache also never
donates its own previous generation into the patch program: with a depth-1
pipeline the in-flight step may still be reading it.

KTPU_INCREMENTAL=0 is the escape hatch: every ensure() returns None and the
kernels take the exact pre-existing dense-hoist paths.
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class IncState(NamedTuple):
    """Device-side incremental-hoist state handed to the kernels.

    Mandatory fields serve the chunked (fit+balanced) route; the optional
    tail serves the rounds route's extra stages (None when the cfg disables
    the stage — None leaves drop out of the pytree, so jit/shard_map keys
    on exactly the populated structure).

    Mask planes (stat_u / fit_u / elig_u) ride PACKED under
    KTPU_PACK_MASKS (ops/bitplane.py): uint32 [U1, S*ceil(N/S/32)] word
    planes in per-shard-local blocks, 8x smaller resident than the dense
    bool rows; raw score matrices (traw_u / naraw_u / img_u) store on the
    bf16 lattice under KTPU_SCORE_DTYPE=bf16 and are upcast to f32 by
    every consumer before reduction.  Under the escape hatches
    (KTPU_PACK_MASKS=0 / KTPU_SCORE_DTYPE=f32) the dense/f32 types below
    apply verbatim."""

    cls: Any      # i32[P] per-pod equivalence-class index (U = padding class)
    req_u: Any    # i32[U1, R] scaled per-class requests
    stat_u: Any   # bool[U1, N] static feasibility per class (packed: u32 words)
    base_u: Any   # f32[U1, N] fit+balanced base scores vs cycle-start usage
    fit_u: Any    # bool[U1, N] fit mask vs cycle-start usage (packed: u32 words)
    elig_u: Any = None   # bool[U1, N] nodesel & node_valid (packed: u32 words)
    traw_u: Any = None   # f32/bf16[U1, N] TaintToleration raw counts
    naraw_u: Any = None  # f32/bf16[U1, N] preferred node-affinity raws
    img_u: Any = None    # f32/bf16[U1, N] ImageLocality static scores


def incremental_enabled() -> bool:
    """KTPU_INCREMENTAL=0 disables the incremental warm path (read per
    cycle, so tests and operators can flip it without a fresh process)."""
    return os.environ.get("KTPU_INCREMENTAL", "") != "0"


# Pod-axis ClusterArrays fields (everything _pod_side builds per unique spec
# and scatters through the class index) — the class view gathers one row per
# class from each.  m_pend ([T, P]) and image_score ([P, N] | [P, 1]) carry
# the pod axis elsewhere and are handled explicitly.
_POD_AXIS_FIELDS = (
    "pod_valid", "pod_req", "pod_prio", "pod_tol_ns", "pod_tol_pref",
    "pod_nodename", "pod_terms", "pod_has_sel", "pod_pref_terms",
    "pod_pref_weights", "pod_group", "pod_match_terms", "pod_match_vals",
    "pod_aff_self", "pod_aff_terms", "pod_anti_terms", "pod_pref_aff_terms",
    "pod_pref_aff_w", "pod_spread_terms", "pod_spread_maxskew",
    "pod_spread_hard", "pod_ports",
)


def class_view(arr, r_u: np.ndarray, pad: int = 0):
    """ClusterArrays whose pod axis is the CLASS axis: row u = the first pod
    of equivalence class u (api/delta.py guarantees rows within a class are
    identical, so WHICH occurrence is immaterial; first keeps it
    deterministic).  `pad` additionally pads the node axis for mesh
    divisibility with the one shared rule set (parallel/mesh.py)."""
    repl = {
        f: np.ascontiguousarray(getattr(arr, f)[r_u]) for f in _POD_AXIS_FIELDS
    }
    repl["m_pend"] = np.ascontiguousarray(arr.m_pend[:, r_u])
    repl["image_score"] = np.ascontiguousarray(arr.image_score[r_u])
    if pad:
        from ..parallel.mesh import NODE_AXIS_FIELDS, pad_field

        d_sentinel = arr.term_counts0.shape[1] - 1
        n = arr.N
        for name in (*NODE_AXIS_FIELDS, "image_score"):
            a = repl.get(name, getattr(arr, name))
            repl[name] = pad_field(name, a, pad, d_sentinel, n)
    return dataclasses.replace(arr, **repl)


@partial(
    jax.jit,
    static_argnames=("want_elig", "want_traw", "want_naraw", "n_shards"),
)
def _static_hoist(cv, want_elig, want_traw, want_naraw, n_shards=1):
    """Usage-independent class matrices from a class-view ClusterArrays —
    the same filter/score functions the kernels' dense preludes apply, so
    row u is bit-identical to any of class u's pod rows in those hoists.

    pod_valid is deliberately NOT folded into `stat`: the kernels re-apply
    per-pod validity from arr.pod_valid (which they already carry), so the
    resident state survives pod_valid-only changes — in particular the gang
    fixpoint (ops/gang.py), which revokes whole groups between iterations.
    pod_group is part of the spec key, so a revocation masks whole classes
    and class-row consistency holds throughout.

    Under KTPU_PACK_MASKS the stat/elig planes leave as uint32 word rows in
    per-shard-local blocks (`n_shards` static — bitplane.pack_blocks), so
    sharding the word axis hands each shard the packed form of its own node
    slice; traw/naraw already arrive on the bf16 lattice from their
    producers (ops/scores.py / ops/assign.py quantize at the source)."""
    from . import bitplane, filters
    from .assign import _preferred_node_affinity_raw
    from .scopes import subphase
    from .scores import taint_prefer_counts

    with subphase("hoist"):
        tm = filters.term_match(cv.sel_mask, cv.sel_kind, cv.node_labels)
        nodesel = filters.node_selection_ok_from(tm, cv)
        stat = (
            cv.node_valid[None, :]
            & filters.taints_ok(cv)
            & nodesel
            & filters.nodename_ok(cv)
        )
        elig = (nodesel & cv.node_valid[None, :]) if want_elig else None
        traw = taint_prefer_counts(cv) if want_traw else None
        naraw = _preferred_node_affinity_raw(cv, tm) if want_naraw else None
        if bitplane.PACK_MASKS:
            stat = bitplane.pack_blocks(stat, n_shards)
            if elig is not None:
                elig = bitplane.pack_blocks(elig, n_shards)
        return stat, elig, traw, naraw


@partial(jax.jit, static_argnames=("cfg", "n_shards"))
def _usage_hoist(req_u, node_used, node_alloc, cfg, n_shards=1):
    """Full [U1, N] fit+balanced hoist — the kernels' base_at/chunk hoist
    vmapped over classes instead of pods (elementwise per (row, node), so
    float32 results are bit-identical to the per-pod dense hoist).  The fit
    MASK leaves packed (per-shard blocks) under KTPU_PACK_MASKS; the f32
    base scores stay dense — they feed top_k directly."""
    from . import bitplane, filters
    from .scopes import subphase
    from .scores import balanced_allocation, fit_score

    with subphase("hoist"):
        requested = node_used[None, :, :] + req_u[:, None, :]
        fit = jax.vmap(filters.fit_ok, (0, None, None))(
            req_u, node_used, node_alloc
        )
        base = cfg.fit_weight * jax.vmap(
            lambda rq, al: fit_score(rq, al, cfg), (0, None)
        )(requested, node_alloc) + cfg.balanced_weight * jax.vmap(
            balanced_allocation, (0, None, None)
        )(requested, node_alloc, cfg.score_resources)
        if bitplane.PACK_MASKS:
            fit = bitplane.pack_blocks(fit, n_shards)
        return base, fit


@partial(jax.jit, static_argnames=("cfg", "n_shards"))
def _patch_hoist(
    base_u, fit_u, req_u, node_used, node_alloc, cols, cfg, n_shards=1
):
    """Recompute the dirty node COLUMNS of the resident usage-side cache.
    `cols` is a pow2-bucketed i32 vector of global node ids, padded with the
    out-of-range sentinel N (clipped on gather, dropped on scatter).  The
    per-column math is the same row-wise formulas as _usage_hoist, so a
    patched matrix equals a full re-hoist bit-for-bit.

    Under KTPU_PACK_MASKS fit_u is a packed word plane: the column
    ASSIGNMENT (mixed set/clear — a dirty node can flip either way) goes
    through bitplane.assign_cols, which builds touched/new word masks from
    a transient dense [U1, N] plane (U-scale — tiny) and merges with two
    bit-ops, so the RESIDENT plane never unpacks.  cols are unique real
    ids plus
    repeated sentinel entries — exactly assign_cols' duplicate contract
    (duplicates carry equal values; the sentinel clips to the drop slot).

    Deliberately NOT donating the previous generation: under the depth-1
    pipeline the in-flight step may still be reading it (the
    donation-aliasing rule in the module docstring)."""
    from . import bitplane, filters
    from .scopes import subphase
    from .scores import balanced_allocation, fit_score

    with subphase("hoist"):
        n = base_u.shape[1]
        safe = jnp.minimum(cols, n - 1)
        cu = node_used[safe]  # [D, R]
        ca = node_alloc[safe]
        fit_c = jax.vmap(filters.fit_ok, (0, None, None))(
            req_u, cu, ca
        )  # [U1, D]
        reqd = cu[None, :, :] + req_u[:, None, :]  # [U1, D, R]
        base_c = cfg.fit_weight * jax.vmap(
            lambda rq: fit_score(rq, ca, cfg)
        )(reqd) + cfg.balanced_weight * jax.vmap(
            lambda rq: balanced_allocation(rq, ca, cfg.score_resources)
        )(reqd)
        base_u = base_u.at[:, cols].set(base_c, mode="drop")
        if bitplane.PACK_MASKS:
            fit_u = bitplane.assign_cols(fit_u, cols, fit_c, n // n_shards)
        else:
            fit_u = fit_u.at[:, cols].set(fit_c, mode="drop")
        return base_u, fit_u


def _round_up_pow2(x: int, minimum: int = 16) -> int:
    v = minimum
    while v < x:
        v *= 2
    return v


_EMPTY = np.empty(0, dtype=np.int64)


def inc_partition_specs(inc: IncState):
    """PartitionSpec tree matching `inc`'s populated structure, resolved
    through the declarative rule table (parallel/partition_rules.py —
    the inc.* rows): node-axis class matrices shard with the ClusterArrays
    node fields; the class index and per-class requests replicate."""
    from ..parallel.partition_rules import incstate_specs

    return incstate_specs(
        inc.elig_u is not None, inc.traw_u is not None,
        inc.naraw_u is not None, inc.img_u is not None,
    )


class HoistCache:
    """Host-side manager of the resident incremental-hoist device state.

    `ensure(arr, meta, cfg)` (HOST ClusterArrays, before device placement)
    returns the IncState for this cycle's step, or None when the
    incremental path does not apply (disabled, no class info, degenerate
    U == P).  Two independent fingerprints drive residency:

      static side — identity of every host array the static matrices read
        (the repo-wide copy-on-write convention makes object identity a
        sound change detector, exactly as the DeltaEncoder's resident
        device-buffer table relies on) plus (U1, N, cfg).  Mismatch →
        full static re-hoist.
      usage side — node_alloc identity (which also keys the int32 rescale:
        api/delta.py caches it by (N, scale)), per-class request equality,
        (U1, N, cfg).  Mismatch → full usage re-hoist.  Match → diff this
        cycle's node_used against the previous encode's rows and patch
        only the dirty columns (object identity short-circuits the diff:
        an untouched cycle patches nothing).

    The row diff against the previous node_used is AUTHORITATIVE (it
    catches every value change regardless of which path produced it);
    api/delta.py's per-sync dirty-node set (meta.dirty_nodes) is the
    observability companion, surfaced in spans/bench artifacts."""

    def __init__(self, mesh=None, tracer=None):
        self.mesh = mesh
        self.tracer = tracer
        self._static_key = None  # (array-ref tuple, meta tuple)
        self._statics = None     # (stat, elig, traw, naraw, img) on device
        self._usage_key = None   # (node_alloc ref, meta tuple)
        self._usage = None       # (base_u, fit_u) on device
        self._req_u_host = None
        self._prev_used = None   # host node_used the usage side matches
        self._cls_ent = None     # (host, device) replicated memo
        self._req_ent = None
        self.stats = {
            "hits": 0, "patched": 0, "full": 0, "static_rebuilds": 0,
            "disabled": 0, "skipped": 0, "patched_cols": 0,
        }
        self.last = {
            "unique_classes": 0, "dirty_node_fraction": 0.0,
            "patched_cols": 0, "action": "none",
        }
        self.history = []

    # -- placement helpers (specs resolved through the partition rule
    # table — parallel/partition_rules.py, the KTPU014 single authority) --
    def _node_sharding(self):
        if self.mesh is None:
            return None
        from ..parallel.partition_rules import sharding_for

        return sharding_for(self.mesh, "inc.stat_u")

    def _rep_sharding(self, qualname: str = "inc.req_u"):
        if self.mesh is None:
            return None
        from ..parallel.partition_rules import sharding_for

        return sharding_for(self.mesh, qualname)

    def _place_node(self, a):
        if a is None:
            return None
        sh = self._node_sharding()
        return jax.device_put(a, sh) if sh is not None else jax.device_put(a)

    def _place_rows(self, a):
        """Explicit placement of [N, R] usage/alloc rows entering the
        jitted hoists — row-sharded under a mesh (the ClusterArrays
        node_used table row), so the jit never implicitly reshards them (the
        KTPU011 transfer-guard rule: every hot-path transfer is explicit)."""
        if self.mesh is None:
            return jax.device_put(a)
        from ..parallel.partition_rules import sharding_for

        return jax.device_put(a, sharding_for(self.mesh, "arr.node_used"))

    def _place_rep(self, name: str, host: np.ndarray,
                   qualname: str = "inc.req_u"):
        """Device copy memoized by host identity/value (the class index and
        per-class requests are identity-stable across steady-state waves via
        the encoder's pad caches), placed through the named table row —
        `inc.cls` shards over the pods axis on a 2-D mesh; `inc.req_u`
        stays replicated."""
        ent = getattr(self, name)
        if ent is not None and (
            ent[0] is host
            or (
                ent[0].shape == host.shape
                and ent[0].dtype == host.dtype
                and np.array_equal(ent[0], host)
            )
        ):
            return ent[1]
        sh = self._rep_sharding(qualname)
        d = jax.device_put(host, sh) if sh is not None else jax.device_put(host)
        setattr(self, name, (host, d))
        return d

    def _note(self, action, u1, frac, ncols, t0, n_nodes=0):
        self.last = {
            "unique_classes": int(u1),
            "dirty_node_fraction": float(frac),
            "patched_cols": int(ncols),
            "action": action,
        }
        if len(self.history) < 512:
            self.history.append(dict(self.last))
        tr = self.tracer
        if tr is not None and getattr(tr, "enabled", False):
            tr.record_span(
                "hoist.update", start=t0, end=time.perf_counter(),
                action=action, unique_classes=int(u1), n_cols=int(ncols),
                n_nodes=int(n_nodes), dirty_node_fraction=float(frac),
            )

    def invalidate(self) -> None:
        """Forget every resident fingerprint and device buffer — the
        crash-restart/takeover rebuild hook (scheduler.py — restore()).

        A restored scheduler re-derives the world from LIST+WATCH; the
        identity-based fingerprints this cache trusts are meaningless
        against the fresh host arrays a new encoder produces, so the first
        post-restore cycle MUST take the full re-hoist path (the forced
        re-fingerprint the crash-only rule requires) instead of patching a
        cache whose lineage died with the old process."""
        self._static_key = None
        self._statics = None
        self._usage_key = None
        self._usage = None
        self._req_u_host = None
        self._prev_used = None
        self._cls_ent = None
        self._req_ent = None

    def summary(self) -> dict:
        """The bench-artifact triple (BENCH_r06 attribution)."""
        fr = sorted(
            h["dirty_node_fraction"] for h in self.history
            if h["action"] in ("patch", "hit", "full")
        )
        return {
            "unique_classes": self.last["unique_classes"],
            "dirty_node_fraction": (fr[len(fr) // 2] if fr else None),
            "hoist_cache_hits": self.stats["hits"],
            "hoist_cache_full": self.stats["full"] + self.stats["static_rebuilds"],
        }

    # -- the per-cycle entry --
    def ensure(self, arr, meta, cfg) -> Optional[IncState]:
        t0 = time.perf_counter()
        if not incremental_enabled():
            self.stats["disabled"] += 1
            return None
        pc = getattr(meta, "pod_class", None)
        r_u = getattr(meta, "class_first_pod", None)
        if pc is None or r_u is None:
            self.stats["skipped"] += 1
            return None
        u1 = int(r_u.shape[0])
        if u1 >= arr.P:
            # degenerate all-pods-unique wave: dedup is a no-op — route the
            # plain dense kernels (tests pin this fallback)
            self.stats["skipped"] += 1
            self._note("skipped_degenerate", u1, 1.0, 0, t0, n_nodes=arr.N)
            return None
        if self.mesh is not None:
            from ..parallel.mesh import mesh_axis_shards

            pod_shards, n_shards = mesh_axis_shards(self.mesh)
        else:
            pod_shards, n_shards = 1, 1
        pad = (-arr.N) % n_shards
        np_nodes = arr.N + pad
        n_real = getattr(meta, "n_nodes", 0) or arr.N

        want_elig = bool(cfg.enable_pairwise)
        want_traw = bool(cfg.enable_taint_score)
        want_naraw = bool(cfg.enable_node_pref)
        want_img = bool(cfg.enable_image) and arr.image_score.shape[1] == arr.N

        # ---- static side (usage-independent; pod_valid excluded — the
        # kernels fold per-pod validity themselves, see _static_hoist) ----
        skey_arrays = (
            pc, r_u, arr.pod_tol_ns, arr.pod_tol_pref,
            arr.pod_nodename, arr.pod_terms, arr.pod_has_sel, arr.sel_mask,
            arr.sel_kind, arr.pod_pref_terms, arr.pod_pref_weights,
            arr.node_valid, arr.node_labels, arr.node_taint_ns,
            arr.node_taint_pref, arr.image_score,
        )
        skey_meta = (
            u1, np_nodes, cfg, want_elig, want_traw, want_naraw, want_img,
        )
        action = None
        if not (
            self._static_key is not None
            and self._static_key[1] == skey_meta
            and all(a is b for a, b in zip(self._static_key[0], skey_arrays))
        ):
            cv = class_view(arr, r_u, pad)
            stat, elig, traw, naraw = _static_hoist(
                cv, want_elig, want_traw, want_naraw, n_shards=n_shards
            )
            img = jnp.asarray(cv.image_score) if want_img else None
            self._statics = tuple(
                self._place_node(x) for x in (stat, elig, traw, naraw, img)
            )
            self._static_key = (skey_arrays, skey_meta)
            self.stats["static_rebuilds"] += 1
            self._usage_key = None  # classes/N/cfg moved — rebuild below
            action = "static_rebuild"

        # ---- usage side (fit + balanced base vs cycle-start usage) ----
        req_u = np.ascontiguousarray(arr.pod_req[r_u])
        ukey_meta = (u1, np_nodes, cfg)
        usage_ok = (
            self._usage_key is not None
            and self._usage_key[1] == ukey_meta
            and self._usage_key[0] is arr.node_alloc
            and np.array_equal(self._req_u_host, req_u)
        )
        used_h = arr.node_used
        dirty = _EMPTY
        if usage_ok and used_h is not self._prev_used:
            dirty = np.flatnonzero((used_h != self._prev_used).any(axis=1))
        req_dev = self._place_rep("_req_ent", req_u)
        if not usage_ok or 2 * len(dirty) >= np_nodes:
            # EXPLICIT host->device staging of the usage rows: the hoist
            # runs on the warm hot path, which must stay clean under
            # jax.transfer_guard("disallow") (KTPU011 — implicit transfers
            # of jit arguments would hide a per-cycle H2D copy here)
            nu = self._place_rows(_pad_rows(used_h, pad))
            na = self._place_rows(_pad_rows(arr.node_alloc, pad))
            base_u, fit_u = _usage_hoist(
                req_dev, nu, na, cfg, n_shards=n_shards
            )
            self._usage = (self._place_node(base_u), self._place_node(fit_u))
            self.stats["full"] += 1
            frac, ncols = 1.0, np_nodes
            action = action or "full"
        elif len(dirty) == 0:
            self.stats["hits"] += 1
            frac, ncols = 0.0, 0
            action = action or "hit"
        else:
            b = _round_up_pow2(len(dirty))
            cols_h = np.full(b, np_nodes, dtype=np.int32)
            cols_h[: len(dirty)] = dirty
            # explicit staging, same KTPU011 rationale as the full hoist;
            # placement through the table's hoist.cols row (replicated)
            if self.mesh is not None:
                from ..parallel.partition_rules import sharding_for

                cols = jax.device_put(
                    cols_h, sharding_for(self.mesh, "hoist.cols"))
            else:
                cols = jax.device_put(cols_h)
            nu = self._place_rows(_pad_rows(used_h, pad))
            na = self._place_rows(_pad_rows(arr.node_alloc, pad))
            base_u, fit_u = _patch_hoist(
                self._usage[0], self._usage[1], req_dev, nu, na, cols, cfg,
                n_shards=n_shards,
            )
            # device_put to the resident sharding is a no-op when GSPMD
            # already produced it there (jax short-circuits equal shardings)
            self._usage = (self._place_node(base_u), self._place_node(fit_u))
            self.stats["hits"] += 1
            self.stats["patched"] += 1
            self.stats["patched_cols"] += len(dirty)
            frac, ncols = len(dirty) / max(1, n_real), len(dirty)
            action = action or "patch"
        self._usage_key = (arr.node_alloc, ukey_meta)
        self._req_u_host = req_u
        self._prev_used = used_h

        # the class index pod-pads with the SAME rule the routed entry
        # applies to the wave (parallel/mesh.py — pad_pods, fill 0): padded
        # pods are pod_valid=False so their class-0 gathers never commit,
        # and inc_applicable's cls.shape[0] == arr.P gate holds against the
        # padded wave.  Sharded over the pods axis on a 2-D mesh (table row
        # inc.cls) — the last whole-P i32 resident replica is gone.
        pod_pad = (-int(pc.shape[0])) % pod_shards
        cls_h = pc if not pod_pad else np.pad(pc, (0, pod_pad))
        cls_dev = self._place_rep("_cls_ent", cls_h, "inc.cls")
        stat, elig, traw, naraw, img = self._statics
        self._note(action, u1, frac, ncols, t0, n_nodes=n_real)
        return IncState(
            cls=cls_dev, req_u=req_dev, stat_u=stat,
            base_u=self._usage[0], fit_u=self._usage[1],
            elig_u=elig, traw_u=traw, naraw_u=naraw, img_u=img,
        )


def _pad_rows(a: np.ndarray, pad: int) -> np.ndarray:
    """Zero-pad the leading (node) axis — the encoder's padding semantics
    for usage/alloc rows (invalid nodes carry zero capacity)."""
    if not pad:
        return a
    return np.pad(a, ((0, pad), (0, 0)))
