"""Per-step kernels for PodTopologySpread, InterPodAffinity, NodePorts — L2's
pairwise half, evaluated inside the commit scan against the running
counts[T, D+1] / anti_counts[T, D+1] / ports_used[N, PT] state.

Shapes: T interned terms, K topology keys, D domains (column D = key absent),
N nodes, C/A1/A2 per-pod constraint slots (padded with -1).

reference: podtopologyspread/filtering.go — calPreFilterState + Filter skew
check; interpodaffinity/filtering.go — satisfyPodAffinity/satisfyPodAntiAffinity
/satisfyExistingPodsAntiAffinity; nodeports/node_ports.go — Fits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _term_rows(counts, node_dom, term_key, term_ids):
    """For each term slot (id or -1): its per-node count row and key presence.

    Returns (cnt[A, N], has_key[A, N], valid[A])."""
    valid = term_ids >= 0
    tids = jnp.maximum(term_ids, 0)
    keys = term_key[tids]  # [A]
    dom_rows = node_dom[keys]  # [A, N]
    D = counts.shape[1] - 1
    cnt = jnp.take_along_axis(counts[tids], dom_rows, axis=1)  # [A, N]
    return cnt, dom_rows < D, valid


def spread_step(counts, node_dom, term_key, spread_terms, maxskew, hard, eligible,
                axis_name=None):
    """-> (ok[N] hard-constraint feasibility, raw[N] score counts).

    Skew rule per DoNotSchedule constraint: placing the pod in node n's domain
    must keep  count(domain) + 1 - minMatch <= maxSkew, where minMatch is the
    min count over domains that contain at least one node passing the pod's
    node-affinity filter (reference: TpKeyToCriticalPaths — the "critical path"
    min).  Nodes lacking the topology key fail hard constraints.
    """
    cnt, has_key, valid = _term_rows(counts, node_dom, term_key, spread_terms)
    elig = eligible[None, :] & has_key
    min_match = jnp.min(jnp.where(elig, cnt, jnp.inf), axis=1)
    if axis_name:
        min_match = jax.lax.pmin(min_match, axis_name)
    min_match = jnp.where(jnp.isinf(min_match), 0.0, min_match)
    ok_c = has_key & (cnt + 1.0 - min_match[:, None] <= maxskew[:, None].astype(jnp.float32))
    ok_c = jnp.where((valid & hard)[:, None], ok_c, True)
    raw = jnp.where((valid[:, None] & has_key), cnt, 0.0).sum(axis=0)
    return jnp.all(ok_c, axis=0), raw


def interpod_required_ok(
    counts, anti_counts, node_dom, term_key, aff_terms, anti_terms, m_pend_col
):
    """-> ok[N]: required pod affinity + own anti-affinity + existing pods'
    anti-affinity (symmetric), against current counts."""
    D = counts.shape[1] - 1
    N = node_dom.shape[1]

    # --- required affinity: every term's domain must already hold a match,
    # unless NO matching pod exists anywhere and the pod matches its own terms
    cnt, has_key, valid = _term_rows(counts, node_dom, term_key, aff_terms)
    ok_a = jnp.where(valid[:, None], has_key & (cnt > 0), True)
    tids = jnp.maximum(aff_terms, 0)
    total_any = jnp.where(valid, counts[tids, :D].sum(axis=1), 0.0).sum()
    self_all = jnp.all(jnp.where(valid, m_pend_col[tids] > 0, True))
    has_aff = valid.any()
    waiver = has_aff & (total_any == 0) & self_all
    aff_ok = jnp.all(ok_a, axis=0) | waiver

    # --- own required anti-affinity: domain must hold no match (absent key
    # cannot be violated)
    cnt2, has_key2, valid2 = _term_rows(counts, node_dom, term_key, anti_terms)
    anti_ok = jnp.all(jnp.where(valid2[:, None], ~(has_key2 & (cnt2 > 0)), True), axis=0)

    # --- existing pods' anti-affinity vs this pod: aggregate per topology key
    # (column D dropped: an anti term on a keyless node can't be violated)
    K = node_dom.shape[0]
    contrib = m_pend_col[:, None] * anti_counts[:, :D]  # [T, D]
    per_key = jax.ops.segment_sum(contrib, term_key, num_segments=K)  # [K, D]
    per_key = jnp.concatenate([per_key, jnp.zeros((K, 1), per_key.dtype)], axis=1)
    blocked = jnp.take_along_axis(per_key, node_dom, axis=1).sum(axis=0)  # [N]
    return aff_ok & anti_ok & (blocked == 0)


def interpod_pref_raw(
    counts, pref_own, node_dom, term_key, pref_terms, pref_w, m_pend_col
):
    """f32[N]: preferred inter-pod affinity raw score (interpodaffinity/
    scoring.go — processExistingPod, both directions):

      own half:       sum_b w_b * counts[t_b, dom(key_b, n)]   (anti: w<0)
      symmetric half: sum_t m[t, p] * pref_own[t, dom(key_t, n)]

    (column D — keyless nodes/pods — excluded on both halves.)"""
    D = counts.shape[1] - 1
    # own preferred terms
    cnt, has_key, valid = _term_rows(counts, node_dom, term_key, pref_terms)
    w = jnp.where(valid, pref_w, 0.0)[:, None]
    own = (jnp.where(has_key, cnt, 0.0) * w).sum(axis=0)
    # existing pods' preferred terms toward this pod, aggregated per key
    K = node_dom.shape[0]
    contrib = m_pend_col[:, None] * pref_own[:, :D]  # [T, D]
    per_key = jax.ops.segment_sum(contrib, term_key, num_segments=K)
    per_key = jnp.concatenate([per_key, jnp.zeros((K, 1), per_key.dtype)], axis=1)
    sym = jnp.take_along_axis(per_key, node_dom, axis=1).sum(axis=0)
    return own + sym


def ports_ok(ports_used, pod_ports_row):
    """-> ok[N]: no hostPort conflict (nodeports/node_ports.go — Fits)."""
    return ~jnp.any(ports_used & pod_ports_row[None, :], axis=1)


def commit_counts(counts, anti_counts, choice, dom_col, m_pend_col, anti_terms):
    """Scatter the committed pod into the pairwise counts (no-op when choice<0).

    `dom_col` is the chosen node's domain per term ([T], already resolved
    globally by the caller — under sharding the owner shard broadcasts it).
    """
    T = counts.shape[0]
    placed = (choice >= 0).astype(counts.dtype)
    counts = counts.at[jnp.arange(T), dom_col].add(placed * m_pend_col)
    # the pod's own anti terms now constrain later pods
    valid2 = (anti_terms >= 0) & (choice >= 0)
    tids2 = jnp.maximum(anti_terms, 0)
    anti_counts = anti_counts.at[tids2, dom_col[tids2]].add(valid2.astype(anti_counts.dtype))
    return counts, anti_counts
