"""Per-step kernels for PodTopologySpread, InterPodAffinity, NodePorts — L2's
pairwise half, evaluated inside the commit scan.

Shapes: T interned terms, K topology keys, D domains (id D = key absent),
N nodes, M matched-term slots, C/A1/A2/B per-pod constraint slots (padded -1).

TPU-first state layout: the scan carries PER-NODE materializations of the
pairwise counts rather than the [T, D+1] per-domain tables —

  cnt_node[T, N]  = counts[t, dom(key_t, n)]   (matching pods in n's domain)
  anti_node[T, N] = anti_counts[t, dom(key_t, n)]
  pref_node[T, N] = pref_own[t, dom(key_t, n)]
  total_t[T]      = counts[t, :D].sum()        (matches anywhere with the key)

because on TPU a 2D take_along_axis gather inside lax.scan costs ~100x a row
dynamic-slice (measured ~135us vs ~3us at [2, 6144]); with per-node state every
per-step read is a row slice + elementwise math, and a commit is a masked add
on O(slots) rows through the STATIC dom_by_term[T, N] = node_dom[term_key] map
(hoisted out of the scan by ops/assign.py).  All sums are integer-valued f32,
so this layout is bit-identical to the per-domain formulation below 2^24.

reference: podtopologyspread/filtering.go — calPreFilterState + Filter skew
check; interpodaffinity/filtering.go — satisfyPodAffinity/satisfyPodAntiAffinity
/satisfyExistingPodsAntiAffinity; nodeports/node_ports.go — Fits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _rows(state_node, has_key_all, term_ids):
    """For each term slot (id or -1): its per-node state row and key presence.

    Returns (cnt[A, N], has_key[A, N], valid[A]).  Row dynamic-slices only —
    no element gathers."""
    valid = term_ids >= 0
    tids = jnp.maximum(term_ids, 0)
    return state_node[tids], has_key_all[tids], valid


def spread_step(cnt_node, has_key_all, spread_terms, maxskew, hard, eligible,
                axis_name=None):
    """-> (ok[N] hard-constraint feasibility, raw[N] score counts).

    Skew rule per DoNotSchedule constraint: placing the pod in node n's domain
    must keep  count(domain) + 1 - minMatch <= maxSkew, where minMatch is the
    min count over domains that contain at least one node passing the pod's
    node-affinity filter (reference: TpKeyToCriticalPaths — the "critical path"
    min).  Nodes lacking the topology key fail hard constraints.
    """
    cnt, has_key, valid = _rows(cnt_node, has_key_all, spread_terms)
    elig = eligible[None, :] & has_key
    min_match = jnp.min(jnp.where(elig, cnt, jnp.inf), axis=1)
    if axis_name:
        min_match = jax.lax.pmin(min_match, axis_name)
    min_match = jnp.where(jnp.isinf(min_match), 0.0, min_match)
    ok_c = has_key & (cnt + 1.0 - min_match[:, None] <= maxskew[:, None].astype(jnp.float32))
    ok_c = jnp.where((valid & hard)[:, None], ok_c, True)
    raw = jnp.where((valid[:, None] & has_key), cnt, 0.0).sum(axis=0)
    return jnp.all(ok_c, axis=0), raw


def interpod_required_ok(
    cnt_node, anti_node, total_t, has_key_all, aff_terms, anti_terms,
    match_terms, match_vals, aff_self,
):
    """-> ok[N]: required pod affinity + own anti-affinity + existing pods'
    anti-affinity (symmetric), against current per-node counts.

    The symmetric half iterates the pod's MATCHED-TERM slots (match_terms[M],
    match_vals[M] — the nonzero entries of this pod's m_pend column, padded
    with -1): blocked[n] = sum_j mv_j * anti_node[mt_j, n] over keyed nodes —
    the scan-time form of interpodaffinity/filtering.go —
    satisfyExistingPodsAntiAffinity."""
    # --- required affinity: every term's domain must already hold a match,
    # unless NO matching pod exists anywhere and the pod matches its own terms
    cnt, has_key, valid = _rows(cnt_node, has_key_all, aff_terms)
    ok_a = jnp.where(valid[:, None], has_key & (cnt > 0), True)
    tids = jnp.maximum(aff_terms, 0)
    total_any = jnp.where(valid, total_t[tids], 0.0).sum()
    self_all = jnp.all(jnp.where(valid, aff_self, True))
    has_aff = valid.any()
    waiver = has_aff & (total_any == 0) & self_all
    aff_ok = jnp.all(ok_a, axis=0) | waiver

    # --- own required anti-affinity: domain must hold no match (absent key
    # cannot be violated)
    cnt2, has_key2, valid2 = _rows(cnt_node, has_key_all, anti_terms)
    anti_ok = jnp.all(jnp.where(valid2[:, None], ~(has_key2 & (cnt2 > 0)), True), axis=0)

    # --- existing pods' anti-affinity vs this pod, via the matched-term slots
    # (keyless nodes dropped: an anti term there can't be violated)
    acnt, ahas_key, avalid = _rows(anti_node, has_key_all, match_terms)
    w = jnp.where(avalid, match_vals, 0.0)[:, None]
    blocked = (jnp.where(ahas_key, acnt, 0.0) * w).sum(axis=0)  # [N]
    return aff_ok & anti_ok & (blocked == 0)


def interpod_pref_raw(
    cnt_node, pref_node, has_key_all, pref_terms, pref_w, match_terms, match_vals
):
    """f32[N]: preferred inter-pod affinity raw score (interpodaffinity/
    scoring.go — processExistingPod, both directions):

      own half:       sum_b w_b * cnt_node[t_b, n]    (anti: w<0)
      symmetric half: sum_j mv_j * pref_node[mt_j, n]

    (keyless nodes excluded on both halves via has_key_all.)"""
    cnt, has_key, valid = _rows(cnt_node, has_key_all, pref_terms)
    w = jnp.where(valid, pref_w, 0.0)[:, None]
    own = (jnp.where(has_key, cnt, 0.0) * w).sum(axis=0)
    pcnt, phas_key, pvalid = _rows(pref_node, has_key_all, match_terms)
    pw = jnp.where(pvalid, match_vals, 0.0)[:, None]
    sym = (jnp.where(phas_key, pcnt, 0.0) * pw).sum(axis=0)
    return own + sym


def ports_ok(ports_used, pod_ports_row):
    """-> ok[N]: no hostPort conflict (nodeports/node_ports.go — Fits)."""
    return ~jnp.any(ports_used & pod_ports_row[None, :], axis=1)


def commit_counts(cnt_node, anti_node, total_t, dom_by_term, n_domains,
                  choice, dom_col, match_terms, match_vals, anti_terms):
    """Absorb the committed pod into the per-node pairwise state (no-op when
    choice < 0).

    `dom_col` is the chosen node's domain per term ([T], already resolved
    globally by the caller — under sharding the owner shard broadcasts it).
    Only the pod's matched-term / own-anti-term rows are touched: row r gains
    its weight at every node sharing the chosen node's domain
    (dom_by_term[r] == dom_col[r]); pad slots add 0 at row 0.
    """
    placed = choice >= 0
    w = jnp.where((match_terms >= 0) & placed, match_vals, 0.0).astype(cnt_node.dtype)
    tids = jnp.maximum(match_terms, 0)
    same = dom_by_term[tids] == dom_col[tids][:, None]  # [M, N]
    cnt_node = cnt_node.at[tids].add(w[:, None] * same)
    # matches-anywhere total: only domains that HAVE the key count
    # (domain id n_domains == "key absent", a static int from the caller)
    keyed = dom_col[tids] < n_domains
    total_t = total_t.at[tids].add(w * keyed)
    # the pod's own anti terms now constrain later pods
    valid2 = (anti_terms >= 0) & placed
    tids2 = jnp.maximum(anti_terms, 0)
    w2 = valid2.astype(anti_node.dtype)
    same2 = dom_by_term[tids2] == dom_col[tids2][:, None]  # [A2, N]
    anti_node = anti_node.at[tids2].add(w2[:, None] * same2)
    return cnt_node, anti_node, total_t
