"""TPUScore client — the scheduler side of the sidecar protocol.

Wraps the gRPC channel with the fallback contract the north star mandates:
deadline exceeded or transport failure raises SidecarUnavailable, and the
caller (scheduler.py) falls back to the stock CPU path — exactly how the
reference tolerates a misbehaving HTTP extender (extender.go ignorable errors).
"""

from __future__ import annotations

from typing import Dict, Optional

import grpc

from ..api.snapshot import Snapshot
from . import tpuscore_pb2 as pb
from .convert import snapshot_to_proto
from .sidecar import SERVICE


class SidecarUnavailable(Exception):
    pass


class TPUScoreClient:
    def __init__(self, address: str):
        self.address = address
        self._channel = grpc.insecure_channel(address)
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE}/Schedule",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleResponse.FromString,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def health(self, timeout_s: float = 2.0) -> pb.HealthResponse:
        try:
            return self._health(pb.HealthRequest(), timeout=timeout_s)
        except grpc.RpcError as e:
            raise SidecarUnavailable(str(e.code())) from e

    def schedule(
        self,
        snap: Snapshot,
        deadline_ms: float = 1000.0,
        gang: bool = True,
        hard_pod_affinity_weight: float = 1.0,
    ) -> Dict[str, Optional[str]]:
        """-> pod uid -> node name (None = unschedulable).  Raises
        SidecarUnavailable on deadline/transport failure (caller falls back)."""
        req = pb.ScheduleRequest(
            snapshot=snapshot_to_proto(snap),
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hard_pod_affinity_weight,
        )
        try:
            resp = self._schedule(req, timeout=deadline_ms / 1e3)
        except grpc.RpcError as e:
            raise SidecarUnavailable(str(e.code())) from e
        return {v.pod_uid: (v.node if v.scheduled else None) for v in resp.verdicts}

    def close(self) -> None:
        self._channel.close()
