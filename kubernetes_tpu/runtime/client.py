"""TPUScore client — the scheduler side of the sidecar protocol.

Wraps the gRPC channel with the fallback contract the north star mandates:
deadline exceeded, transport failure, or a cold (still-compiling) sidecar
raises SidecarUnavailable and the caller (scheduler.py) falls back to the
stock CPU path — exactly how the reference tolerates a misbehaving HTTP
extender (extender.go ignorable errors).

Round-3 sessions: the client ships the cluster once, then per cycle only the
spec-interned wave + the bound-pod diff (tpuscore.proto — SessionDelta).  The
diff is computed here against the last acknowledged state; any gap the server
reports (resync_required — e.g. it restarted) triggers ONE full-snapshot
retry inside the same call, which is the crash-only reconnect contract."""

from __future__ import annotations

import dataclasses
import uuid
from typing import Dict, List, Optional, Tuple

import grpc

from ..api import types as t
from ..api.snapshot import Snapshot
from . import tpuscore_pb2 as pb
from .convert import node_to_proto, pod_to_proto
from .sidecar import SERVICE


class SidecarUnavailable(Exception):
    pass


# one shared field list + comparator with the encoder's bind-absorb
# revalidation — the two drift checks cannot diverge
from ..api.delta import bound_spec_fields_match as _spec_fields_match


class TPUScoreClient:
    def __init__(self, address: str, session: bool = True):
        from .sidecar import TPUScoreServer

        self.address = address
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", TPUScoreServer.MAX_MSG),
                ("grpc.max_send_message_length", TPUScoreServer.MAX_MSG),
            ],
        )
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE}/Schedule",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleResponse.FromString,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )
        # session state (None session_id = legacy stateless requests)
        from ..api.snapshot import SpecInterner

        self._interner = SpecInterner()  # persistent wave spec interning
        self._spec_msgs: Dict[Tuple, object] = {}  # canonical key -> pb.Pod
        self.session_id = uuid.uuid4().hex if session else ""
        self._epoch = 0
        self._synced = False
        self._nodes_fp: Optional[Tuple] = None
        self._last_wave: Dict[str, t.Pod] = {}
        self._known_bound: Dict[str, t.Pod] = {}
        self._last_assign: Dict[str, str] = {}  # server's previous assignment
        self._fp_refs: Tuple = ()
        self.stats = {
            "full": 0, "delta": 0, "resync": 0, "not_ready": 0,
            "binds_compressed": 0, "binds_explicit": 0,
        }

    def health(self, timeout_s: float = 2.0) -> pb.HealthResponse:
        try:
            return self._health(pb.HealthRequest(), timeout=timeout_s)
        except grpc.RpcError as e:
            raise SidecarUnavailable(str(e.code())) from e

    @staticmethod
    def _trace_metadata():
        """The W3C-traceparent analog for the sidecar hop: stamp the ACTIVE
        span's trace_id/span_id (the scheduler's batch.cycle is current when
        schedule() runs) into gRPC metadata.  The server rebuilds the span
        context from it (sidecar.py — _parent_ctx), so a sidecar-routed
        wave renders as ONE connected Perfetto tree instead of an orphan
        root per RPC — the ROADMAP open item."""
        from ..scheduler.tracing import current_span

        sp = current_span()
        if sp is None:
            return None
        return (
            ("ktpu-trace-id", sp.trace_id),
            ("ktpu-span-id", sp.span_id),
        )

    # --- request builders ---
    def _wave_msg(self, pods) -> pb.InternedWave:
        """The spec-interned wave message: per-template
        canonical keying AND pb.Pod serialization happen once, not per cycle
        (steady-state waves re-send only uids + spec indices)."""
        reps, inv, rep_keys = self._interner.group(pods)
        if len(self._spec_msgs) > 4 * (len(rep_keys) + 256):
            self._spec_msgs.clear()
        specs = []
        for rep, k in zip(reps, rep_keys):
            msg = self._spec_msgs.get(k)
            if msg is None:
                msg = pod_to_proto(rep)
                msg.ClearField("name")
                msg.ClearField("uid")
                self._spec_msgs[k] = msg
            specs.append(msg)
        msg = pb.InternedWave(specs=specs)
        msg.uids.extend(p.uid for p in pods)
        msg.spec_idx.extend(inv.tolist())
        return msg

    def _full_request(self, snap: Snapshot, deadline_ms, gang, hpaw):
        req = pb.ScheduleRequest(
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hpaw,
            session_id=self.session_id,
            epoch=self._epoch,
            wave=self._wave_msg(snap.pending_pods),
        )
        req.snapshot.nodes.extend(node_to_proto(n) for n in snap.nodes)
        req.snapshot.bound_pods.extend(pod_to_proto(p) for p in snap.bound_pods)
        req.snapshot.pod_groups.extend(
            pb.PodGroup(name=g.name, min_member=g.min_member)
            for g in snap.pod_groups.values()
        )
        self.stats["full"] += 1
        return req

    def _delta_request(self, snap: Snapshot, deadline_ms, gang, hpaw):
        req = pb.ScheduleRequest(
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hpaw,
            session_id=self.session_id,
            epoch=self._epoch,
            wave=self._wave_msg(snap.pending_pods),
        )
        req.delta.SetInParent()  # presence even when the diff is empty
        d = req.delta
        d.base_epoch = self._epoch - 1
        covered = set()
        for p in snap.bound_pods:
            known = self._known_bound.get(p.uid)
            if known is not None:
                # already on the server — but a REPLACED object (label or
                # other metadata update to a bound pod; the in-process
                # encoder's `rec[_OBJ] is not q` case) must ship so the
                # session doesn't silently diverge from the stateless path
                if known is p or (
                    p.node_name == known.node_name and _spec_fields_match(known, p)
                ):
                    continue
                d.added_bound.append(pod_to_proto(p))
                continue
            prev = self._last_wave.get(p.uid)
            if prev is not None and _spec_fields_match(prev, p):
                # the common steady-state bind: if it lands exactly where
                # the server's previous response assigned it, it rides the
                # bind_prev_assignment compression instead of a Bind message
                if self._last_assign.get(p.uid) == p.node_name:
                    covered.add(p.uid)
                else:
                    d.binds.add(pod_uid=p.uid, node=p.node_name)
            else:
                # never seen pending (external bind), or the bound copy
                # drifted from the wave spec (e.g. label update raced the
                # bind): ship the object itself
                d.added_bound.append(pod_to_proto(p))
        if covered:
            exc = [uid for uid in self._last_assign if uid not in covered]
            if len(exc) < len(covered):
                d.bind_prev_assignment = True
                d.bind_prev_except.extend(exc)
                self.stats["binds_compressed"] += len(covered)
            else:
                # a mostly-unbound assignment: the exception list would
                # outweigh the saved Bind messages — ship binds explicitly
                for uid in covered:
                    d.binds.add(pod_uid=uid, node=self._last_assign[uid])
        self.stats["binds_explicit"] += len(d.binds)
        bound_now = {p.uid for p in snap.bound_pods}
        d.deleted_uids.extend(
            uid for uid in self._known_bound if uid not in bound_now
        )
        req.snapshot.pod_groups.extend(
            pb.PodGroup(name=g.name, min_member=g.min_member)
            for g in snap.pod_groups.values()
        )
        self.stats["delta"] += 1
        return req

    # --- the call ---
    def schedule(
        self,
        snap: Snapshot,
        deadline_ms: float = 1000.0,
        gang: bool = True,
        hard_pod_affinity_weight: float = 1.0,
    ) -> Dict[str, Optional[str]]:
        """-> pod uid -> node name (None = unschedulable).  Raises
        SidecarUnavailable on deadline/transport failure or a still-compiling
        sidecar (caller falls back)."""
        from ..api.delta import raw_fingerprints, raw_keepalive_refs
        from ..api.volumes import resolve_snapshot

        if not self.session_id:
            return self._schedule_stateless(
                resolve_snapshot(snap), deadline_ms, gang,
                hard_pod_affinity_weight,
            )
        # fingerprint the RAW cluster (resolution rebuilds node objects per
        # cycle whenever volume/DRA state exists) with the SAME helpers the
        # delta encoder conditions on, then resolve for the wire
        nodes_fp = raw_fingerprints(snap)
        raw_snap = snap
        snap = resolve_snapshot(snap)
        self._epoch += 1
        if self._synced and nodes_fp == self._nodes_fp:
            req = self._delta_request(
                snap, deadline_ms, gang, hard_pod_affinity_weight
            )
        else:
            req = self._full_request(
                snap, deadline_ms, gang, hard_pod_affinity_weight
            )
        md = self._trace_metadata()
        try:
            resp = self._schedule(req, timeout=deadline_ms / 1e3, metadata=md)
            if resp.resync_required:
                # server lost the session (restart / eviction): reconnect by
                # re-sending the full snapshot once, same call
                self.stats["resync"] += 1
                self._synced = False
                req = self._full_request(
                    snap, deadline_ms, gang, hard_pod_affinity_weight
                )
                resp = self._schedule(
                    req, timeout=deadline_ms / 1e3, metadata=md
                )
                if resp.resync_required:
                    raise SidecarUnavailable("resync loop")
        except grpc.RpcError as e:
            # transport/deadline failure: the server may or may not have
            # applied this epoch — force a full resync next cycle
            self._synced = False
            raise SidecarUnavailable(str(e.code())) from e
        # the server applied this request's state even when answering
        # not_ready — record it so the next cycle's diff is correct
        self._synced = True
        if nodes_fp != self._nodes_fp:
            # (re)synchronized against a new raw state: pin every object the
            # fingerprints id() so address reuse can never alias them; on
            # matching cycles the existing refs already pin the same objects
            self._fp_refs = raw_keepalive_refs(raw_snap)
            self._nodes_fp = nodes_fp
        self._last_wave = {p.uid: p for p in snap.pending_pods}
        self._known_bound = {p.uid: p for p in snap.bound_pods}
        if resp.not_ready:
            self.stats["not_ready"] += 1
            self._last_assign = {}  # no assignment to echo next cycle
            raise SidecarUnavailable("sidecar compiling (not ready)")
        # aligned-array verdicts: assignment[i] is a node index (our own node
        # list's order) for pending pod i in the order we sent the wave
        names = [nd.name for nd in snap.nodes]
        out = {
            p.uid: (names[c] if c >= 0 else None)
            for p, c in zip(snap.pending_pods, resp.assignment)
        }
        self._last_assign = {u: n for u, n in out.items() if n is not None}
        return out

    def _schedule_stateless(self, snap, deadline_ms, gang, hpaw):
        from .convert import snapshot_to_proto

        req = pb.ScheduleRequest(
            snapshot=snapshot_to_proto(snap),
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hpaw,
        )
        try:
            resp = self._schedule(
                req, timeout=deadline_ms / 1e3,
                metadata=self._trace_metadata(),
            )
        except grpc.RpcError as e:
            raise SidecarUnavailable(str(e.code())) from e
        return {v.pod_uid: (v.node if v.scheduled else None) for v in resp.verdicts}

    def close(self) -> None:
        self._channel.close()
