"""TPUScore client — the scheduler side of the sidecar protocol.

Wraps the gRPC channel with the fallback contract the north star mandates:
deadline exceeded, transport failure, or a cold (still-compiling) sidecar
raises SidecarUnavailable and the caller (scheduler.py) falls back to the
stock CPU path — exactly how the reference tolerates a misbehaving HTTP
extender (extender.go ignorable errors).

Round-3 sessions: the client ships the cluster once, then per cycle only the
spec-interned wave + the bound-pod diff (tpuscore.proto — SessionDelta).  The
diff is computed here against the last acknowledged state; any gap the server
reports (resync_required — e.g. it restarted) triggers ONE full-snapshot
retry inside the same call, which is the crash-only reconnect contract."""

from __future__ import annotations

import dataclasses
import random
import time
import uuid
from typing import Dict, List, Optional, Tuple

import grpc

from ..api import types as t
from ..api.snapshot import Snapshot
from .. import chaos
from . import tpuscore_pb2 as pb
from .convert import node_to_proto, pod_to_proto
from .sidecar import SERVICE


class SidecarUnavailable(Exception):
    """The caller must fall back to the in-process CPU branch.

    retryable distinguishes transport-shaped failures (a drop, a deadline,
    a partial response — a fresh attempt may land) from structural ones (a
    still-compiling sidecar, a resync loop, an exhausted failure budget —
    retrying inside the same cycle cannot help)."""

    def __init__(self, msg: str, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable


# one shared field list + comparator with the encoder's bind-absorb
# revalidation — the two drift checks cannot diverge
from ..api.delta import bound_spec_fields_match as _spec_fields_match


class TPUScoreClient:
    """retry/degrade contract (the Borg/Omega failure-is-common posture):
    each schedule() retries transport failures up to max_attempts with
    capped exponential backoff + jitter (seeded — reproducible waits), then
    raises for the per-cycle CPU fallback.  failure_budget CONSECUTIVE
    exhausted calls trip the circuit: the channel is marked degraded and
    schedule() raises immediately (no dial, no deadline wait) until
    degraded_cooldown_s elapses, after which one half-open probe attempt is
    allowed; any success fully resets the budget."""

    def __init__(self, address: str, session: bool = True, metrics=None,
                 max_attempts: int = 3, backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0, failure_budget: int = 3,
                 degraded_cooldown_s: float = 30.0, sleep_fn=time.sleep):
        from ..scheduler.metrics import Metrics
        from .sidecar import TPUScoreServer

        self.address = address
        self.metrics = metrics if metrics is not None else Metrics()
        self.max_attempts = max(1, max_attempts)
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.failure_budget = max(1, failure_budget)
        self.degraded_cooldown_s = degraded_cooldown_s
        self._sleep = sleep_fn
        self._retry_rng = random.Random(0xC4A05)  # jitter only; never a decision
        self.degraded = False
        self._degraded_until = 0.0
        self._consecutive_failures = 0
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", TPUScoreServer.MAX_MSG),
                ("grpc.max_send_message_length", TPUScoreServer.MAX_MSG),
            ],
        )
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE}/Schedule",
            request_serializer=pb.ScheduleRequest.SerializeToString,
            response_deserializer=pb.ScheduleResponse.FromString,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )
        # session state (None session_id = legacy stateless requests)
        from ..api.snapshot import SpecInterner

        self._interner = SpecInterner()  # persistent wave spec interning
        self._spec_msgs: Dict[Tuple, object] = {}  # canonical key -> pb.Pod
        self.session_id = uuid.uuid4().hex if session else ""
        self._epoch = 0
        self._synced = False
        self._nodes_fp: Optional[Tuple] = None
        self._last_wave: Dict[str, t.Pod] = {}
        self._known_bound: Dict[str, t.Pod] = {}
        self._last_assign: Dict[str, str] = {}  # server's previous assignment
        self._fp_refs: Tuple = ()
        self.stats = {
            "full": 0, "delta": 0, "resync": 0, "not_ready": 0,
            "binds_compressed": 0, "binds_explicit": 0, "retries": 0,
        }

    def health(self, timeout_s: float = 2.0) -> pb.HealthResponse:
        """Health RPC.  A transport failure is never swallowed silently: it
        increments sidecar_health_failures_total, counts toward the failure
        budget (marking the channel degraded when exhausted), and forces a
        full session resync on the next schedule() — the server may have
        restarted and lost the session (the reconnect-after-health-failure
        contract; tests/test_chaos.py asserts it)."""
        try:
            if chaos.enabled():
                chaos.poke("sidecar.health", metrics=self.metrics)
            resp = self._health(pb.HealthRequest(), timeout=timeout_s)
        except (grpc.RpcError, chaos.FaultInjected) as e:
            self.metrics.inc("sidecar_health_failures_total")
            self._synced = False
            self._note_failure()
            code = str(e.code()) if isinstance(e, grpc.RpcError) else "INJECTED"
            raise SidecarUnavailable(code, retryable=True) from e
        self._note_success()
        return resp

    # --- failure budget / circuit state ---
    def _note_failure(self) -> None:
        self._consecutive_failures += 1
        if not self.degraded and self._consecutive_failures >= self.failure_budget:
            self.degraded = True
            self._degraded_until = time.monotonic() + self.degraded_cooldown_s
            self.metrics.inc("sidecar_degraded_total")
            chaos.record_recovery(
                "sidecar.rpc", "degrade", metrics=self.metrics,
                failures=self._consecutive_failures,
            )

    def _note_success(self) -> None:
        self._consecutive_failures = 0
        if self.degraded:
            self.degraded = False
            self.metrics.inc("sidecar_degraded_recovered_total")
            chaos.record_recovery("sidecar.rpc", "reconnect", metrics=self.metrics)

    def _check_degraded(self) -> bool:
        """While degraded, fail fast (no dial, no deadline wait) so every
        cycle takes the in-process CPU branch immediately; after the
        cooldown one half-open probe call is let through — its success
        resets the budget, its failure re-arms the cooldown.  Returns True
        when THIS call is the half-open probe: the caller restricts it to a
        single attempt (probing a still-dead sidecar must not pay the full
        retry ladder inside one scheduling cycle)."""
        if not self.degraded:
            return False
        now = time.monotonic()
        if now < self._degraded_until:
            self.metrics.inc("sidecar_degraded_skips_total")
            raise SidecarUnavailable(
                "degraded (failure budget exhausted)", retryable=False
            )
        self._degraded_until = now + self.degraded_cooldown_s  # re-arm
        return True

    def _backoff_sleep(self, attempt: int) -> None:
        """Capped exponential backoff with multiplicative jitter between
        retry attempts (seeded RNG: reproducible waits, never a decision
        input)."""
        d = min(self.backoff_cap_s, self.backoff_base_s * (2 ** attempt))
        self._sleep(d * (1.0 + self._retry_rng.random()))

    def _retrying(self, attempt_fn, max_attempts: Optional[int] = None):
        attempts = max_attempts if max_attempts is not None else self.max_attempts
        for attempt in range(attempts):
            try:
                out = attempt_fn()
            except SidecarUnavailable as e:
                if not e.retryable:
                    # structural (still compiling / resync loop): the
                    # transport is fine — neither retry nor budget
                    raise
                self.metrics.inc("sidecar_rpc_failures_total")
                if attempt + 1 < attempts:
                    self.stats["retries"] = self.stats.get("retries", 0) + 1
                    self._backoff_sleep(attempt)
                    continue
                self._note_failure()
                raise
            if attempt > 0:
                chaos.record_recovery(
                    "sidecar.rpc", "retry", metrics=self.metrics,
                    attempts=attempt + 1,
                )
            self._note_success()
            return out

    @staticmethod
    def _trace_metadata():
        """The W3C-traceparent analog for the sidecar hop: stamp the ACTIVE
        span's trace_id/span_id (the scheduler's batch.cycle is current when
        schedule() runs) into gRPC metadata.  The server rebuilds the span
        context from it (sidecar.py — _parent_ctx), so a sidecar-routed
        wave renders as ONE connected Perfetto tree instead of an orphan
        root per RPC — the ROADMAP open item."""
        from ..scheduler.tracing import current_span

        sp = current_span()
        if sp is None:
            return None
        return (
            ("ktpu-trace-id", sp.trace_id),
            ("ktpu-span-id", sp.span_id),
        )

    # --- request builders ---
    def _wave_msg(self, pods) -> pb.InternedWave:
        """The spec-interned wave message: per-template
        canonical keying AND pb.Pod serialization happen once, not per cycle
        (steady-state waves re-send only uids + spec indices)."""
        reps, inv, rep_keys = self._interner.group(pods)
        if len(self._spec_msgs) > 4 * (len(rep_keys) + 256):
            self._spec_msgs.clear()
        specs = []
        for rep, k in zip(reps, rep_keys):
            msg = self._spec_msgs.get(k)
            if msg is None:
                msg = pod_to_proto(rep)
                msg.ClearField("name")
                msg.ClearField("uid")
                self._spec_msgs[k] = msg
            specs.append(msg)
        msg = pb.InternedWave(specs=specs)
        msg.uids.extend(p.uid for p in pods)
        msg.spec_idx.extend(inv.tolist())
        return msg

    def _full_request(self, snap: Snapshot, deadline_ms, gang, hpaw):
        req = pb.ScheduleRequest(
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hpaw,
            session_id=self.session_id,
            epoch=self._epoch,
            wave=self._wave_msg(snap.pending_pods),
        )
        req.snapshot.nodes.extend(node_to_proto(n) for n in snap.nodes)
        req.snapshot.bound_pods.extend(pod_to_proto(p) for p in snap.bound_pods)
        req.snapshot.pod_groups.extend(
            pb.PodGroup(name=g.name, min_member=g.min_member)
            for g in snap.pod_groups.values()
        )
        self.stats["full"] += 1
        return req

    def _delta_request(self, snap: Snapshot, deadline_ms, gang, hpaw):
        req = pb.ScheduleRequest(
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hpaw,
            session_id=self.session_id,
            epoch=self._epoch,
            wave=self._wave_msg(snap.pending_pods),
        )
        req.delta.SetInParent()  # presence even when the diff is empty
        d = req.delta
        d.base_epoch = self._epoch - 1
        covered = set()
        for p in snap.bound_pods:
            known = self._known_bound.get(p.uid)
            if known is not None:
                # already on the server — but a REPLACED object (label or
                # other metadata update to a bound pod; the in-process
                # encoder's `rec[_OBJ] is not q` case) must ship so the
                # session doesn't silently diverge from the stateless path
                if known is p or (
                    p.node_name == known.node_name and _spec_fields_match(known, p)
                ):
                    continue
                d.added_bound.append(pod_to_proto(p))
                continue
            prev = self._last_wave.get(p.uid)
            if prev is not None and _spec_fields_match(prev, p):
                # the common steady-state bind: if it lands exactly where
                # the server's previous response assigned it, it rides the
                # bind_prev_assignment compression instead of a Bind message
                if self._last_assign.get(p.uid) == p.node_name:
                    covered.add(p.uid)
                else:
                    d.binds.add(pod_uid=p.uid, node=p.node_name)
            else:
                # never seen pending (external bind), or the bound copy
                # drifted from the wave spec (e.g. label update raced the
                # bind): ship the object itself
                d.added_bound.append(pod_to_proto(p))
        if covered:
            exc = [uid for uid in self._last_assign if uid not in covered]
            if len(exc) < len(covered):
                d.bind_prev_assignment = True
                d.bind_prev_except.extend(exc)
                self.stats["binds_compressed"] += len(covered)
            else:
                # a mostly-unbound assignment: the exception list would
                # outweigh the saved Bind messages — ship binds explicitly
                for uid in covered:
                    d.binds.add(pod_uid=uid, node=self._last_assign[uid])
        self.stats["binds_explicit"] += len(d.binds)
        bound_now = {p.uid for p in snap.bound_pods}
        d.deleted_uids.extend(
            uid for uid in self._known_bound if uid not in bound_now
        )
        req.snapshot.pod_groups.extend(
            pb.PodGroup(name=g.name, min_member=g.min_member)
            for g in snap.pod_groups.values()
        )
        self.stats["delta"] += 1
        return req

    # --- the call ---
    def schedule(
        self,
        snap: Snapshot,
        deadline_ms: float = 1000.0,
        gang: bool = True,
        hard_pod_affinity_weight: float = 1.0,
    ) -> Dict[str, Optional[str]]:
        """-> pod uid -> node name (None = unschedulable).  Transport-shaped
        failures retry in-call with capped backoff + jitter; raises
        SidecarUnavailable once retries exhaust, the failure budget trips
        (degraded channel — fails fast until the cooldown), or the sidecar
        is still compiling (caller falls back to the CPU branch)."""
        from ..api.volumes import resolve_snapshot

        probing = self._check_degraded()
        attempts = 1 if probing else None  # half-open: exactly one attempt
        if not self.session_id:
            rsnap = resolve_snapshot(snap)
            return self._retrying(
                lambda: self._schedule_stateless(
                    rsnap, deadline_ms, gang, hard_pod_affinity_weight
                ),
                max_attempts=attempts,
            )
        return self._retrying(
            lambda: self._schedule_session_once(
                snap, deadline_ms, gang, hard_pod_affinity_weight
            ),
            max_attempts=attempts,
        )

    def _schedule_session_once(
        self,
        snap: Snapshot,
        deadline_ms: float,
        gang: bool,
        hard_pod_affinity_weight: float,
    ) -> Dict[str, Optional[str]]:
        from ..api.delta import raw_fingerprints, raw_keepalive_refs
        from ..api.volumes import resolve_snapshot
        # fingerprint the RAW cluster (resolution rebuilds node objects per
        # cycle whenever volume/DRA state exists) with the SAME helpers the
        # delta encoder conditions on, then resolve for the wire
        nodes_fp = raw_fingerprints(snap)
        raw_snap = snap
        snap = resolve_snapshot(snap)
        self._epoch += 1
        if self._synced and nodes_fp == self._nodes_fp:
            req = self._delta_request(
                snap, deadline_ms, gang, hard_pod_affinity_weight
            )
        else:
            req = self._full_request(
                snap, deadline_ms, gang, hard_pod_affinity_weight
            )
        md = self._trace_metadata()
        try:
            fault = (
                chaos.poke("sidecar.rpc", metrics=self.metrics)
                if chaos.enabled() else None
            )
            resp = self._schedule(req, timeout=deadline_ms / 1e3, metadata=md)
            if resp.resync_required:
                # server lost the session (restart / eviction): reconnect by
                # re-sending the full snapshot once, same call
                self.stats["resync"] += 1
                self._synced = False
                req = self._full_request(
                    snap, deadline_ms, gang, hard_pod_affinity_weight
                )
                resp = self._schedule(
                    req, timeout=deadline_ms / 1e3, metadata=md
                )
                if resp.resync_required:
                    raise SidecarUnavailable("resync loop")
            if fault is not None and fault.action == "partial":
                # truncated response (a connection cut mid-stream): the
                # validation below must catch it, never decode it
                del resp.assignment[len(resp.assignment) // 2:]
        except (grpc.RpcError, chaos.FaultInjected) as e:
            # transport/deadline failure: the server may or may not have
            # applied this epoch — force a full resync next cycle
            self._synced = False
            code = str(e.code()) if isinstance(e, grpc.RpcError) else "INJECTED"
            raise SidecarUnavailable(code, retryable=True) from e
        # the server applied this request's state even when answering
        # not_ready — record it so the next cycle's diff is correct
        self._synced = True
        if nodes_fp != self._nodes_fp:
            # (re)synchronized against a new raw state: pin every object the
            # fingerprints id() so address reuse can never alias them; on
            # matching cycles the existing refs already pin the same objects
            self._fp_refs = raw_keepalive_refs(raw_snap)
            self._nodes_fp = nodes_fp
        self._last_wave = {p.uid: p for p in snap.pending_pods}
        self._known_bound = {p.uid: p for p in snap.bound_pods}
        if resp.not_ready:
            self.stats["not_ready"] += 1
            self._last_assign = {}  # no assignment to echo next cycle
            raise SidecarUnavailable("sidecar compiling (not ready)")
        if len(resp.assignment) != len(snap.pending_pods):
            # a partial/truncated response: zip() below would silently drop
            # the tail's verdicts (pods would vanish into the preemption
            # path on a healthy cluster) — treat it as the transport
            # failure it is and resync
            self._synced = False
            self.metrics.inc("sidecar_partial_responses_total")
            raise SidecarUnavailable(
                f"partial response ({len(resp.assignment)} verdicts for "
                f"{len(snap.pending_pods)} pods)", retryable=True,
            )
        # aligned-array verdicts: assignment[i] is a node index (our own node
        # list's order) for pending pod i in the order we sent the wave
        names = [nd.name for nd in snap.nodes]
        out = {
            p.uid: (names[c] if c >= 0 else None)
            for p, c in zip(snap.pending_pods, resp.assignment)
        }
        self._last_assign = {u: n for u, n in out.items() if n is not None}
        return out

    def _schedule_stateless(self, snap, deadline_ms, gang, hpaw):
        from .convert import snapshot_to_proto

        req = pb.ScheduleRequest(
            snapshot=snapshot_to_proto(snap),
            deadline_ms=deadline_ms,
            gang=gang,
            hard_pod_affinity_weight=hpaw,
        )
        try:
            fault = (
                chaos.poke("sidecar.rpc", metrics=self.metrics)
                if chaos.enabled() else None
            )
            resp = self._schedule(
                req, timeout=deadline_ms / 1e3,
                metadata=self._trace_metadata(),
            )
            if fault is not None and fault.action == "partial":
                del resp.verdicts[len(resp.verdicts) // 2:]
        except (grpc.RpcError, chaos.FaultInjected) as e:
            code = str(e.code()) if isinstance(e, grpc.RpcError) else "INJECTED"
            raise SidecarUnavailable(code, retryable=True) from e
        if len(resp.verdicts) != len(snap.pending_pods):
            self.metrics.inc("sidecar_partial_responses_total")
            raise SidecarUnavailable(
                f"partial response ({len(resp.verdicts)} verdicts for "
                f"{len(snap.pending_pods)} pods)", retryable=True,
            )
        return {v.pod_uid: (v.node if v.scheduled else None) for v in resp.verdicts}

    def close(self) -> None:
        self._channel.close()
