"""proto <-> object-model conversion for the TPUScore sidecar protocol."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import types as t
from ..api.snapshot import Snapshot
from . import tpuscore_pb2 as pb


# ---------- to proto ----------

def _quantities(d: Dict[str, int]):
    return [pb.Quantity(resource=k, value=int(v)) for k, v in d.items()]


def _labels(d: Dict[str, str]):
    return [pb.Label(key=k, value=v) for k, v in d.items()]


def _selector(sel: Optional[t.LabelSelector]) -> pb.LabelSelector:
    if sel is None:
        return pb.LabelSelector(present=False)
    return pb.LabelSelector(
        present=True,
        match_labels=[pb.Label(key=k, value=v) for k, v in sel.match_labels],
        match_expressions=[
            pb.LabelSelectorRequirement(key=e.key, op=e.operator, values=list(e.values))
            for e in sel.match_expressions
        ],
    )


def _nst(term: t.NodeSelectorTerm) -> pb.NodeSelectorTerm:
    return pb.NodeSelectorTerm(
        match_expressions=[
            pb.LabelSelectorRequirement(key=e.key, op=e.operator, values=list(e.values))
            for e in term.match_expressions
        ]
    )


def _pat(term: t.PodAffinityTerm) -> pb.PodAffinityTerm:
    return pb.PodAffinityTerm(
        topology_key=term.topology_key,
        selector=_selector(term.label_selector),
        namespaces=list(term.namespaces),
    )


def pod_to_proto(p: t.Pod) -> pb.Pod:
    msg = pb.Pod(
        name=p.name,
        namespace=p.namespace,
        uid=p.uid,
        requests=_quantities(p.requests),
        labels=_labels(p.labels),
        node_name=p.node_name,
        priority=p.priority,
        tolerations=[
            pb.Toleration(key=x.key, op=x.operator, value=x.value, effect=x.effect)
            for x in p.tolerations
        ],
        node_selector=[pb.Label(key=k, value=v) for k, v in p.node_selector],
        host_ports=[pb.HostPort(protocol=pr, port=po) for pr, po in p.host_ports],
        scheduling_gates=list(p.scheduling_gates),
        pod_group=p.pod_group,
        topology_spread=[
            pb.TopologySpreadConstraint(
                max_skew=c.max_skew,
                topology_key=c.topology_key,
                when_unsatisfiable=c.when_unsatisfiable,
                selector=_selector(c.label_selector),
            )
            for c in p.topology_spread
        ],
        images=list(p.images),
    )
    if p.affinity:
        msg.required_node_terms.extend(_nst(x) for x in p.affinity.required_node_terms)
        msg.preferred_node_terms.extend(
            pb.PreferredSchedulingTerm(weight=x.weight, preference=_nst(x.preference))
            for x in p.affinity.preferred_node_terms
        )
        msg.required_pod_affinity.extend(_pat(x) for x in p.affinity.required_pod_affinity)
        msg.required_pod_anti_affinity.extend(
            _pat(x) for x in p.affinity.required_pod_anti_affinity
        )
        msg.preferred_pod_affinity.extend(
            pb.WeightedPodAffinityTerm(weight=x.weight, term=_pat(x.term))
            for x in p.affinity.preferred_pod_affinity
        )
        msg.preferred_pod_anti_affinity.extend(
            pb.WeightedPodAffinityTerm(weight=x.weight, term=_pat(x.term))
            for x in p.affinity.preferred_pod_anti_affinity
        )
    return msg


def node_to_proto(n: t.Node) -> pb.Node:
    return pb.Node(
        name=n.name,
        allocatable=_quantities(n.allocatable),
        labels=_labels(n.labels),
        taints=[pb.Taint(key=x.key, value=x.value, effect=x.effect) for x in n.taints],
        unschedulable=n.unschedulable,
        images=[pb.ImageEntry(name=k, size_bytes=v) for k, v in n.images.items()],
    )


def clone_pod(rep: t.Pod, name: str, uid: str, node_name: str = "") -> t.Pod:
    """types.pod_clone with the session-path fields (the one shared clone
    idiom — field objects stay shared with the rep)."""
    return t.pod_clone(rep, name=name, uid=uid, node_name=node_name)


def wave_parts_from_proto(
    msg: pb.InternedWave, rep_cache: Optional[dict] = None
) -> Tuple[List[str], List[t.Pod], "np.ndarray"]:
    """-> (uids, reps, inv) WITHOUT materializing per-pod objects — the
    encoder's pregrouped path (api/delta.py — encode_pregrouped) consumes
    the interned form directly.  `rep_cache` memoizes decoded reps by
    serialized spec bytes so successive waves reuse identical objects."""
    import numpy as np

    reps = []
    for s in msg.specs:
        if rep_cache is None:
            reps.append(pod_from_proto(s))
            continue
        kb = s.SerializeToString()
        rep = rep_cache.get(kb)
        if rep is None:
            if len(rep_cache) > 4096:
                rep_cache.clear()
            rep = pod_from_proto(s)
            rep_cache[kb] = rep
        reps.append(rep)
    inv = np.asarray(msg.spec_idx, dtype=np.int64)
    return list(msg.uids), reps, inv


def wave_from_proto(
    msg: pb.InternedWave, rep_cache: Optional[dict] = None
) -> List[t.Pod]:
    """Pod names are synthesized from uids (the session path keys verdicts by
    wave position, never by name).

    The per-pod clone is __new__ + __dict__ copy — ~4x cheaper than
    copy.copy's reduce machinery at 50k pods/wave, and field objects stay
    shared with the rep (what the encoder's identity-level interning keys
    on).  `rep_cache` (per-session) memoizes decoded reps by serialized
    spec bytes so SUCCESSIVE waves reuse the same rep objects — steady-state
    waves then hit the identity level instead of re-canonicalizing ~every
    spec every wave.  Plain dict cache: the client memoizes its spec
    messages, so identical specs serialize to identical bytes in practice;
    a miss just decodes again."""
    reps = []
    for s in msg.specs:
        if rep_cache is None:
            reps.append(pod_from_proto(s))
            continue
        kb = s.SerializeToString()
        rep = rep_cache.get(kb)
        if rep is None:
            if len(rep_cache) > 4096:
                rep_cache.clear()
            rep = pod_from_proto(s)
            rep_cache[kb] = rep
        reps.append(rep)
    out: List[t.Pod] = []
    append = out.append
    clone = t.pod_clone
    for uid, si in zip(msg.uids, msg.spec_idx):
        append(clone(reps[si], name=uid, uid=uid))
    return out


def snapshot_to_proto(s: Snapshot) -> pb.Snapshot:
    return pb.Snapshot(
        nodes=[node_to_proto(n) for n in s.nodes],
        pending_pods=[pod_to_proto(p) for p in s.pending_pods],
        bound_pods=[pod_to_proto(p) for p in s.bound_pods],
        pod_groups=[
            pb.PodGroup(name=g.name, min_member=g.min_member) for g in s.pod_groups.values()
        ],
    )


# ---------- from proto ----------

def _from_selector(msg: pb.LabelSelector) -> Optional[t.LabelSelector]:
    if not msg.present:
        return None
    return t.LabelSelector(
        match_labels=tuple((l.key, l.value) for l in msg.match_labels),
        match_expressions=tuple(
            t.LabelSelectorRequirement(key=e.key, operator=e.op, values=tuple(e.values))
            for e in msg.match_expressions
        ),
    )


def _from_nst(msg: pb.NodeSelectorTerm) -> t.NodeSelectorTerm:
    return t.NodeSelectorTerm(
        match_expressions=tuple(
            t.NodeSelectorRequirement(key=e.key, operator=e.op, values=tuple(e.values))
            for e in msg.match_expressions
        )
    )


def _from_pat(msg: pb.PodAffinityTerm) -> t.PodAffinityTerm:
    return t.PodAffinityTerm(
        topology_key=msg.topology_key,
        label_selector=_from_selector(msg.selector),
        namespaces=tuple(msg.namespaces),
    )


def pod_from_proto(msg: pb.Pod) -> t.Pod:
    affinity = None
    if (
        msg.required_node_terms
        or msg.preferred_node_terms
        or msg.required_pod_affinity
        or msg.required_pod_anti_affinity
        or msg.preferred_pod_affinity
        or msg.preferred_pod_anti_affinity
    ):
        affinity = t.Affinity(
            required_node_terms=tuple(_from_nst(x) for x in msg.required_node_terms),
            preferred_node_terms=tuple(
                t.PreferredSchedulingTerm(weight=x.weight, preference=_from_nst(x.preference))
                for x in msg.preferred_node_terms
            ),
            required_pod_affinity=tuple(_from_pat(x) for x in msg.required_pod_affinity),
            required_pod_anti_affinity=tuple(
                _from_pat(x) for x in msg.required_pod_anti_affinity
            ),
            preferred_pod_affinity=tuple(
                t.WeightedPodAffinityTerm(weight=x.weight, term=_from_pat(x.term))
                for x in msg.preferred_pod_affinity
            ),
            preferred_pod_anti_affinity=tuple(
                t.WeightedPodAffinityTerm(weight=x.weight, term=_from_pat(x.term))
                for x in msg.preferred_pod_anti_affinity
            ),
        )
    return t.Pod(
        name=msg.name,
        namespace=msg.namespace or "default",
        uid=msg.uid,
        requests={q.resource: int(q.value) for q in msg.requests},
        labels={l.key: l.value for l in msg.labels},
        node_name=msg.node_name,
        priority=msg.priority,
        tolerations=tuple(
            t.Toleration(key=x.key, operator=x.op or "Equal", value=x.value, effect=x.effect)
            for x in msg.tolerations
        ),
        node_selector=tuple(sorted((l.key, l.value) for l in msg.node_selector)),
        affinity=affinity,
        topology_spread=tuple(
            t.TopologySpreadConstraint(
                max_skew=c.max_skew,
                topology_key=c.topology_key,
                when_unsatisfiable=c.when_unsatisfiable or t.DO_NOT_SCHEDULE,
                label_selector=_from_selector(c.selector),
            )
            for c in msg.topology_spread
        ),
        host_ports=tuple((h.protocol, h.port) for h in msg.host_ports),
        scheduling_gates=tuple(msg.scheduling_gates),
        pod_group=msg.pod_group,
        images=tuple(msg.images),
    )


def node_from_proto(msg: pb.Node) -> t.Node:
    return t.Node(
        name=msg.name,
        allocatable={q.resource: int(q.value) for q in msg.allocatable},
        labels={l.key: l.value for l in msg.labels},
        taints=tuple(
            t.Taint(key=x.key, value=x.value, effect=x.effect) for x in msg.taints
        ),
        unschedulable=msg.unschedulable,
        images={e.name: int(e.size_bytes) for e in msg.images},
    )


def snapshot_from_proto(msg: pb.Snapshot) -> Snapshot:
    return Snapshot(
        nodes=[node_from_proto(n) for n in msg.nodes],
        pending_pods=[pod_from_proto(p) for p in msg.pending_pods],
        bound_pods=[pod_from_proto(p) for p in msg.bound_pods],
        pod_groups={
            g.name: t.PodGroup(name=g.name, min_member=g.min_member) for g in msg.pod_groups
        },
    )
