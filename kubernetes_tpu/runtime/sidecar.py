"""TPUScore gRPC sidecar — L4.

The JAX process that owns the TPU: receives activeQ + NodeInfo snapshots over
gRPC, runs the batched filter/score/commit kernels, streams binding verdicts
back.  Single-writer by construction: one server thread owns the device
(SURVEY.md §5 race-detection note — design the host side single-writer),
gRPC concurrency is serialized through a lock rather than locks in the engine.

Crash-only: the server keeps no state a reconnecting client cannot re-send —
every request carries the full snapshot (delta streaming is a planned
optimization; the contract already allows it because verdicts are pure
functions of the snapshot).

Service stubs are hand-wired with grpc.method_handlers_generic_handler (the
image has grpcio but not grpc_tools' codegen plugin).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from . import tpuscore_pb2 as pb
from .convert import snapshot_from_proto

SERVICE = "tpuscore.TPUScore"


class _Engine:
    """The in-process scheduling engine the server fronts."""

    def __init__(self):
        self._lock = threading.Lock()

    def schedule(self, snap, gang: bool, hard_pod_affinity_weight: float = 1.0):
        from ..api.snapshot import encode_snapshot
        from ..ops import schedule_batch
        from ..ops.gang import schedule_with_gangs
        from ..ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config

        with self._lock:  # single writer on the device
            # the weight applies in BOTH stages: pre-bound pods at encode
            # time, batch-committed pods through the kernel config
            arr, meta = encode_snapshot(
                snap, hard_pod_affinity_weight=hard_pod_affinity_weight
            )
            base = dataclasses.replace(
                DEFAULT_SCORE_CONFIG, hard_pod_affinity_weight=hard_pod_affinity_weight
            )
            cfg = infer_score_config(arr, base)
            if gang:
                choices, _ = schedule_with_gangs(arr, cfg)
            else:
                choices = np.asarray(schedule_batch(arr, cfg)[0])
            return choices, meta


class TPUScoreServer:
    def __init__(self, address: str = "127.0.0.1:0", engine: Optional[_Engine] = None):
        self.engine = engine or _Engine()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        handlers = {
            "Schedule": grpc.unary_unary_rpc_method_handler(
                self._schedule,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleResponse.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                self._health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(address)

    # --- RPCs ---
    def _schedule(self, request: pb.ScheduleRequest, context) -> pb.ScheduleResponse:
        t0 = time.perf_counter()
        snap = snapshot_from_proto(request.snapshot)
        uid_of = {p.name: p.uid for p in snap.pending_pods}
        hpaw = (
            request.hard_pod_affinity_weight
            if request.HasField("hard_pod_affinity_weight")
            else 1.0
        )
        choices, meta = self.engine.schedule(snap, request.gang, hpaw)
        resp = pb.ScheduleResponse()
        for k in range(meta.n_pods):
            c = int(choices[k])
            name = meta.pod_names[k]
            resp.verdicts.append(
                pb.Verdict(
                    pod_uid=uid_of[name],
                    node=meta.node_names[c] if c >= 0 else "",
                    scheduled=c >= 0,
                )
            )
        resp.elapsed_ms = (time.perf_counter() - t0) * 1e3
        return resp

    def _health(self, request, context) -> pb.HealthResponse:
        import jax

        devs = jax.devices()
        return pb.HealthResponse(ok=True, platform=devs[0].platform, device_count=len(devs))

    # --- lifecycle ---
    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


def main() -> None:  # pragma: no cover - manual entry point
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", default="127.0.0.1:50151")
    args = ap.parse_args()
    srv = TPUScoreServer(args.listen)
    port = srv.start()
    print(f"tpuscore sidecar listening on port {port}")
    threading.Event().wait()


if __name__ == "__main__":
    main()
