"""TPUScore gRPC sidecar — L4.

The JAX process that owns the TPU: receives activeQ + NodeInfo snapshots over
gRPC, runs the batched filter/score/commit kernels, streams binding verdicts
back.  Single-writer by construction: device work is serialized through one
lock; session bookkeeping lives under a separate fast lock so control-plane
answers (not_ready / resync_required) never wait on a compile.

Round-3 session/delta protocol (the watch-cache analog on the wire — see
tpuscore.proto): a session-holding client ships the cluster once, then per
cycle only the spec-interned pending wave + the bound-pod diff.  Server-side,
each session owns a resident api/delta.py — DeltaEncoder, so the device
encode is incremental exactly like the in-process scheduler path.  Crash-only:
the server may drop any session at any time and answer resync_required; the
client re-sends the full snapshot (storage/cacher — rebuilt from LIST on any
gap).  Cold sessions warm up in the background (encode + compile + one run);
until then Schedule answers not_ready immediately and the client takes the
mandated CPU fallback — /readyz reflects this state instead of lying.

Service stubs are hand-wired with grpc.method_handlers_generic_handler (the
image has grpcio but not grpc_tools' codegen plugin; messages come from
protoc --python_out).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional

import grpc
import numpy as np

from ..api import types as t
from ..api.snapshot import Snapshot
from . import tpuscore_pb2 as pb
from ..analysis.lockcheck import make_lock
from .convert import (
    clone_pod,
    node_from_proto,
    pod_from_proto,
    snapshot_from_proto,
    wave_from_proto,
    wave_parts_from_proto,
)

SERVICE = "tpuscore.TPUScore"


class _Session:
    """Per-client resident cluster state + encoder (single-writer: mutated
    only under _Engine._state_lock)."""

    def __init__(self, hpaw: float):
        from ..api.delta import DeltaEncoder

        self.enc = DeltaEncoder(hard_pod_affinity_weight=hpaw)
        self.hpaw = hpaw
        self.nodes: List[t.Node] = []
        self.bound: Dict[str, t.Pod] = {}
        # uid -> the wave pod's spec REP (no per-pod objects exist on the
        # session path; bind copies clone from the rep with clone_pod)
        self.last_wave: Dict[str, t.Pod] = {}
        # uid -> node NAME assigned by the previous response — the referent
        # of the delta's bind_prev_assignment compression
        self.last_assign: Dict[str, str] = {}
        # serialized-spec-bytes -> decoded rep Pod (convert.wave_from_proto):
        # keeps rep OBJECTS stable across waves so the resident encoder's
        # identity-level interning hits instead of re-canonicalizing
        self.rep_cache: Dict[bytes, t.Pod] = {}
        self.pod_groups: Dict[str, t.PodGroup] = {}
        self.epoch = 0
        self.ready = False
        self.warming = False
        # speculative wholesale-bind clones, built in the BACKGROUND right
        # after a response ships (exact: derived from last_assign +
        # last_wave, both frozen at response time; exceptions only remove
        # entries).  The next delta's bind_prev_assignment consumes them
        # off its critical path — at 50k binds the clone loop alone is
        # ~0.15 s of decode otherwise.
        self.prebind: Optional[Dict[str, t.Pod]] = None
        self.prebind_epoch = -1
        self.prebind_done = threading.Event()


class _ResyncRequired(Exception):
    pass


class _Engine:
    """The in-process scheduling engine the server fronts.

    warmup_threshold: wave x nodes size above which a COLD session (no
    compiled kernel for that coarse shape yet) answers not_ready and compiles
    in the background instead of blowing the client's deadline; smaller
    problems compile inline (sub-second on any backend)."""

    # LRU-evicted.  MEMORY NOTE: each session pins its full cluster (node/
    # bound-pod objects), a resident DeltaEncoder, and that encoder's device
    # buffers — at north-star scale roughly (20k Node objects + padded
    # [P, N]-adjacent arrays) per session, so 4 sessions ≈ 4x the snapshot
    # residency.  There is no byte accounting; the cap IS the bound, and
    # sidecar_sessions_resident exposes the current count.
    MAX_SESSIONS = 4

    def __init__(self, warmup_threshold: int = 4_000_000):
        from ..scheduler.metrics import Metrics

        self._lock = make_lock("_Engine._lock")  # device owner
        self._state_lock = make_lock("_Engine._state_lock")  # session bookkeeping
        self._sessions: Dict[str, _Session] = {}  # insertion == LRU order
        self.warmup_threshold = warmup_threshold
        self._compiled: set = set()  # coarse (P_bucket, N_bucket, gang) shapes
        # per-phase latency histograms (decode/encode/step/readback) — the
        # round-3 loopback waves showed a 1.85->3.22 s spread with no way to
        # attribute it; these are served over HealthServer /metrics
        self.metrics = Metrics()

    # --- legacy stateless path ---
    def schedule(self, snap, gang: bool, hard_pod_affinity_weight: float = 1.0):
        from ..api.snapshot import encode_snapshot
        from ..ops import schedule_batch
        from ..ops.gang import schedule_with_gangs
        from ..ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config

        with self._lock:  # single writer on the device
            # the weight applies in BOTH stages: pre-bound pods at encode
            # time, batch-committed pods through the kernel config
            arr, meta = encode_snapshot(
                snap, hard_pod_affinity_weight=hard_pod_affinity_weight
            )
            base = dataclasses.replace(
                DEFAULT_SCORE_CONFIG, hard_pod_affinity_weight=hard_pod_affinity_weight
            )
            cfg = infer_score_config(arr, base)
            if gang:
                choices, _ = schedule_with_gangs(arr, cfg)
            else:
                choices = np.asarray(schedule_batch(arr, cfg)[0])
            return choices, meta

    # --- session path ---
    def apply_request(self, request: pb.ScheduleRequest):
        """Update (or create) the session's cluster state from the request.
        Returns (session, wave_pods).  Raises _ResyncRequired on any gap."""
        hpaw = (
            request.hard_pod_affinity_weight
            if request.HasField("hard_pod_affinity_weight")
            else 1.0
        )
        with self._state_lock:
            sess0 = self._sessions.get(request.session_id)
            rep_cache = sess0.rep_cache if sess0 is not None else {}
        # decode outside the lock; rep_cache is only ever touched by this
        # session's requests (one client), so the dict is effectively
        # single-writer.  The dict is carried into a full-sync's fresh
        # session below so resyncs keep rep objects identity-stable.
        # No per-pod objects are materialized: the encoder consumes the
        # interned (uids, reps, inv) form directly (encode_pregrouped).
        wave = wave_parts_from_proto(request.wave, rep_cache)
        # wait for this session's prebind OUTSIDE the state lock — other
        # sessions' RPCs and /readyz must never queue behind one session's
        # background clone build.  One client sends serially, so sess0's
        # event/epoch are the ones the delta below will consult.
        if (
            sess0 is not None
            and request.HasField("delta")
            and request.delta.bind_prev_assignment
            and sess0.prebind_epoch == request.delta.base_epoch
        ):
            sess0.prebind_done.wait(timeout=30.0)
        with self._state_lock:
            sess = self._sessions.get(request.session_id)
            if sess is not None:
                # refresh LRU position (dead clients' sessions age out)
                self._sessions.pop(request.session_id)
                self._sessions[request.session_id] = sess
            if request.HasField("delta"):
                d = request.delta
                if sess is None or sess.epoch != d.base_epoch or sess.hpaw != hpaw:
                    raise _ResyncRequired()
                if d.bind_prev_assignment:
                    # the client echoes our own previous assignment: bind it
                    # wholesale minus the exception list (no per-pod strings
                    # crossed the wire).  Prefer the clones precomputed in
                    # the background after the previous response (exact for
                    # this base_epoch); fall back to cloning inline.
                    exc = set(d.bind_prev_except)
                    pre = None
                    if (
                        sess.prebind_epoch == d.base_epoch
                        and sess.prebind_done.is_set()  # waited pre-lock
                    ):
                        pre = sess.prebind
                    if pre is not None:
                        self.metrics.inc("sidecar_prebind_hits")
                        for uid, p in pre.items():
                            if uid not in exc:
                                sess.bound[uid] = p
                    else:
                        for uid, node in sess.last_assign.items():
                            if uid in exc:
                                continue
                            rep = sess.last_wave.get(uid)
                            if rep is None:
                                raise _ResyncRequired()
                            sess.bound[uid] = clone_pod(rep, uid, uid, node)
                for b in d.binds:
                    rep = sess.last_wave.get(b.pod_uid)
                    if rep is None:
                        raise _ResyncRequired()
                    # spec fields verified client-side; the bound copy shares
                    # the rep's field objects, so the encoder's bind-absorb
                    # `is`-checks hold
                    sess.bound[b.pod_uid] = clone_pod(
                        rep, b.pod_uid, b.pod_uid, b.node
                    )
                for uid in d.deleted_uids:
                    sess.bound.pop(uid, None)
                for msg in d.added_bound:
                    p = pod_from_proto(msg)
                    sess.bound[p.uid] = p
            else:
                # full sync (re)builds the session; LRU-evict beyond the cap
                # (crash-only: an evicted client just resyncs).  The decode
                # rep cache survives the rebuild — resync must not cost the
                # encoder its identity-level warmth.
                sess = _Session(hpaw)
                sess.rep_cache = rep_cache
                self._sessions[request.session_id] = sess
                while len(self._sessions) > self.MAX_SESSIONS:
                    oldest = next(iter(self._sessions))
                    del self._sessions[oldest]
                sess.nodes = [node_from_proto(n) for n in request.snapshot.nodes]
                sess.bound = {
                    p.uid: p
                    for p in (pod_from_proto(m) for m in request.snapshot.bound_pods)
                }
            sess.pod_groups = {
                g.name: t.PodGroup(name=g.name, min_member=g.min_member)
                for g in request.snapshot.pod_groups
            }
            uids, reps, inv = wave
            sess.last_wave = dict(zip(uids, (reps[i] for i in inv.tolist())))
            sess.epoch = request.epoch
            # capture the encode inputs UNDER the state lock: the warmup
            # thread (and run_session) must never iterate sess.bound while a
            # later RPC's delta mutates it
            view = (list(sess.bound.values()), dict(sess.pod_groups))
            return sess, wave, view

    def coarse_shape_parts(self, sess: _Session, wave, gang: bool):
        from ..api.snapshot import _bucket

        uids, _reps, _inv = wave
        return (_bucket(len(uids)), _bucket(len(sess.nodes)), gang)

    def run_session(self, sess: _Session, wave, gang: bool, view=None):
        """One wave: encode -> dispatch -> readback, PIPELINED across
        requests.  The device lock covers only host encode + kernel
        DISPATCH (JAX queues device work asynchronously and in order); the
        blocking readback happens OUTSIDE the lock, so while wave k's step
        executes on the device, wave k+1's decode and host encode proceed
        — the host/device overlap that separated the round-3 loopback
        waves (~2 s serial) from the <1 s budget.  Per-session encoder
        state stays single-writer: one client per session sends serially,
        and cross-session encoders are distinct objects.  Gang waves take
        the DEVICE-side fixpoint (ops/gang.py — gang_fixpoint_device: the
        revoke-one loop as a lax.while_loop), so config 5 dispatches
        asynchronously and overlaps exactly like every other wave — the
        round-4 verdict's "the gang path cannot overlap" gap."""
        from ..ops import schedule_batch
        from ..ops.gang import gang_fixpoint_device
        from ..ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config

        uids, reps, inv = wave
        if view is None:  # direct callers (tests) outside an RPC
            with self._state_lock:
                view = (list(sess.bound.values()), dict(sess.pod_groups))
        bound, groups = view
        with self._lock:
            t0 = time.perf_counter()
            arr, meta = sess.enc.encode_device_pregrouped(
                sess.nodes, bound, groups, uids, reps, inv,
            )
            base = dataclasses.replace(
                DEFAULT_SCORE_CONFIG, hard_pod_affinity_weight=sess.hpaw
            )
            cfg = infer_score_config(arr, base)
            t1 = time.perf_counter()
            self.metrics.observe("sidecar_encode_seconds", t1 - t0)
            if gang:
                choices_dev = gang_fixpoint_device(arr, cfg)[0]  # async
            else:
                choices_dev = schedule_batch(arr, cfg)[0]  # async dispatch
            t2 = time.perf_counter()
            self.metrics.observe("sidecar_dispatch_seconds", t2 - t1)
            self._compiled.add(self.coarse_shape_parts(sess, wave, gang))
        # blocking transfer outside the device lock: waits on the device
        # stream while the next request encodes
        choices = np.asarray(choices_dev)
        self.metrics.observe("sidecar_step_seconds", time.perf_counter() - t2)
        return choices, meta

    def warmup(self, sess: _Session, wave, gang: bool, view=None) -> None:
        """Background: encode + compile + run once, then mark ready.  The
        results are discarded — the client already took the CPU fallback for
        this cycle; what survives is the jit cache and the session's resident
        encoder state.  A FAILED warmup drops the session (crash-only): the
        client's next request resyncs instead of hitting a session that
        claims ready but cannot serve."""
        try:
            self.run_session(sess, wave, gang, view)
        except Exception:  # noqa: BLE001 — crash-only containment
            with self._state_lock:
                sess.warming = False
                for sid, s in list(self._sessions.items()):
                    if s is sess:
                        del self._sessions[sid]
            return
        with self._state_lock:
            sess.warming = False
            sess.ready = True

    @property
    def ready(self) -> bool:
        with self._state_lock:
            return all(s.ready for s in self._sessions.values())


def _parent_ctx(context):
    """Rebuild the client-stamped span context from gRPC metadata
    (runtime/client.py — _trace_metadata).  Returns a SpanContext or None;
    with it, the server-side schedule span joins the scheduler's trace tree
    — one connected Perfetto render across the wire hop."""
    from ..scheduler.tracing import SpanContext

    try:
        md = {k: v for k, v in (context.invocation_metadata() or ())}
    except Exception:  # noqa: BLE001 — tests pass bare mocks
        return None
    tid, sid = md.get("ktpu-trace-id"), md.get("ktpu-span-id")
    if tid and sid:
        return SpanContext(tid, sid)
    return None


class TPUScoreServer:
    # full snapshots at north-star scale exceed gRPC's 4 MB default
    MAX_MSG = 256 * 1024 * 1024

    def __init__(self, address: str = "127.0.0.1:0", engine: Optional[_Engine] = None,
                 collector=None):
        from ..scheduler.tracing import Tracer

        self.engine = engine or _Engine()
        # span tracing: the default process collector unless injected (the
        # in-process loopback tests share one collector with the scheduler,
        # which is what makes the cross-hop tree assertable)
        self.tracer = Tracer(collector, component="sidecar")
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4),
            options=[
                ("grpc.max_receive_message_length", self.MAX_MSG),
                ("grpc.max_send_message_length", self.MAX_MSG),
            ],
        )
        handlers = {
            "Schedule": grpc.unary_unary_rpc_method_handler(
                self._schedule,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleResponse.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                self._health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(address)

    # --- RPCs ---
    def _schedule(self, request: pb.ScheduleRequest, context) -> pb.ScheduleResponse:
        """Schedule RPC entry, traced under the CLIENT's span context when
        the request metadata carries one (trace_id/span_id stamped by
        runtime/client.py): the sidecar's work renders inside the
        scheduler's batch.cycle tree instead of as a disconnected root."""
        if not self.tracer.enabled:
            return self._schedule_inner(request, context)
        with self.tracer.span(
            "sidecar.schedule",
            parent=_parent_ctx(context),
            session=request.session_id or "stateless",
            pods=len(request.wave.uids) or len(request.snapshot.pending_pods),
        ):
            return self._schedule_inner(request, context)

    def _schedule_inner(
        self, request: pb.ScheduleRequest, context
    ) -> pb.ScheduleResponse:
        t0 = time.perf_counter()
        if not request.session_id:
            return self._schedule_stateless(request, t0)
        try:
            sess, wave, view = self.engine.apply_request(request)
        except _ResyncRequired:
            return pb.ScheduleResponse(resync_required=True)
        m = self.engine.metrics
        m.observe("sidecar_decode_seconds", time.perf_counter() - t0)
        with self.engine._state_lock:
            m.set("sidecar_sessions_resident", len(self.engine._sessions))
        if not sess.ready:
            eng = self.engine
            small = (
                len(wave[0]) * max(1, len(sess.nodes)) < eng.warmup_threshold
            )
            spawn = False
            with eng._state_lock:  # check-then-act atomic across the RPC pool
                if small or eng.coarse_shape_parts(sess, wave, request.gang) in eng._compiled:
                    # compile affordable (or already paid): serve synchronously
                    sess.ready = True
                elif not sess.warming:
                    sess.warming = True
                    spawn = True
            if spawn:
                threading.Thread(
                    target=eng.warmup,
                    args=(sess, wave, request.gang, view),
                    daemon=True,
                ).start()
            if not sess.ready:
                return pb.ScheduleResponse(not_ready=True, epoch=sess.epoch)
        choices, meta = self.engine.run_session(sess, wave, request.gang, view)
        # aligned-array verdicts: node index per wave pod in REQUEST order
        # (meta.pod_perm maps device order -> request order; node indices are
        # the session's node-list order == the client's own node list)
        resp = pb.ScheduleResponse(epoch=sess.epoch)
        out = np.full(meta.n_pods, -1, dtype=np.int64)
        out[meta.pod_perm] = np.asarray(choices[: meta.n_pods], dtype=np.int64)
        resp.assignment.extend(out.tolist())
        # remember what we just assigned: the next delta may bind it by
        # reference (bind_prev_assignment) instead of 50k Bind messages.
        # Built OUTSIDE the state lock (50k-entry dict; control-plane
        # answers must not wait on it) — only the reference store is locked.
        node_names = [nd.name for nd in sess.nodes]
        last_assign = {
            uid: node_names[int(c)]
            for uid, c in zip(wave[0], out.tolist())
            if c >= 0
        }
        # speculatively build the wholesale-bind clones in the background:
        # the worker captures ITS OWN references (last_assign/last_wave are
        # only ever rebound, never mutated, by later requests), so a racing
        # next request sees either a completed exact precompute for this
        # epoch or falls back to inline cloning.  Session fields stay
        # single-writer-under-the-state-lock (the class invariant): the
        # epoch/event pair is published under the lock here, and the worker
        # takes the lock for its one result write.
        ev = threading.Event()
        state_lock = self.engine._state_lock
        with state_lock:
            sess.last_assign = last_assign
            sess.prebind = None
            sess.prebind_done = ev
            sess.prebind_epoch = sess.epoch
        wave_map = sess.last_wave

        def _prebind(assign=last_assign, wave_map=wave_map, ev=ev,
                     sess=sess, lock=state_lock):
            try:
                pre: Optional[Dict[str, t.Pod]] = {}
                for uid, node in assign.items():
                    rep = wave_map.get(uid)
                    if rep is None:
                        pre = None  # missing rep: inline path raises resync
                        break
                    pre[uid] = clone_pod(rep, uid, uid, node)
                with lock:
                    if sess.prebind_done is ev:  # not superseded
                        sess.prebind = pre
            finally:
                # set() even on failure: a waiter must fall back to the
                # inline path (prebind None), never block out the timeout
                ev.set()

        threading.Thread(target=_prebind, daemon=True).start()
        resp.elapsed_ms = (time.perf_counter() - t0) * 1e3
        m.observe("sidecar_schedule_seconds", time.perf_counter() - t0)
        return resp

    def _schedule_stateless(self, request, t0) -> pb.ScheduleResponse:
        snap = snapshot_from_proto(request.snapshot)
        if request.wave.uids or request.wave.specs:
            snap.pending_pods = wave_from_proto(request.wave)
        uid_of = {p.name: p.uid for p in snap.pending_pods}
        hpaw = (
            request.hard_pod_affinity_weight
            if request.HasField("hard_pod_affinity_weight")
            else 1.0
        )
        choices, meta = self.engine.schedule(snap, request.gang, hpaw)
        resp = pb.ScheduleResponse()
        self._fill_verdicts(resp, choices, meta, uid_of)
        resp.elapsed_ms = (time.perf_counter() - t0) * 1e3
        return resp

    @staticmethod
    def _fill_verdicts(resp, choices, meta, uid_of) -> None:
        for k in range(meta.n_pods):
            c = int(choices[k])
            name = meta.pod_names[k]
            resp.verdicts.append(
                pb.Verdict(
                    pod_uid=uid_of[name],
                    node=meta.node_names[c] if c >= 0 else "",
                    scheduled=c >= 0,
                )
            )

    def _health(self, request, context) -> pb.HealthResponse:
        import jax

        devs = jax.devices()
        return pb.HealthResponse(
            ok=True,
            platform=devs[0].platform,
            device_count=len(devs),
            ready=self.engine.ready,
        )

    # --- lifecycle ---
    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class HealthServer:
    """component-base health + metrics + zpages endpoints: /healthz /readyz
    /livez (apiserver/pkg/server/healthz), Prometheus-text /metrics, and the
    zpages pair /statusz (component + uptime) and /flagz (effective
    configuration) — "every binary serves /metrics, /healthz|readyz|livez"
    plus component-base/zpages (SURVEY.md §5)."""

    def __init__(self, address: str = "127.0.0.1:0", metrics=None,
                 ready_check=None, component: str = "tpuscore-sidecar",
                 flags=None):
        import http.server

        self.metrics = metrics
        self.ready_check = ready_check or (lambda: True)
        self.component = component
        self.flags = dict(flags or {})
        self._started_at = time.time()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path in ("/healthz", "/livez"):
                    body, code = b"ok", 200
                elif self.path == "/readyz":
                    ok = outer.ready_check()
                    body, code = (b"ok", 200) if ok else (b"not ready", 503)
                elif self.path == "/metrics":
                    body, code = outer._render_metrics().encode(), 200
                elif self.path == "/statusz":
                    up = time.time() - outer._started_at
                    body = (
                        f"{outer.component}\nstatus: "
                        f"{'ok' if outer.ready_check() else 'not ready'}\n"
                        f"uptime_seconds: {up:.1f}\n"
                    ).encode()
                    code = 200
                elif self.path == "/flagz":
                    body = "".join(
                        f"{k}={v}\n" for k, v in sorted(outer.flags.items())
                    ).encode() or b"(no flags)\n"
                    code = 200
                else:
                    body, code = b"not found", 404
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        host, _, port = address.partition(":")
        self._httpd = http.server.HTTPServer((host, int(port or 0)), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    def _render_metrics(self) -> str:
        # one renderer for every exposition point: the full registry in
        # Prometheus text format — counters, gauges, labeled series and
        # streaming-histogram cumulative buckets (scheduler/metrics.py —
        # Metrics.expose_text; the apiserver's /metrics route serves the
        # identical body)
        if self.metrics is None:
            return "\n"
        return self.metrics.expose_text()

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()


def main() -> None:  # pragma: no cover - manual entry point
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", default="127.0.0.1:50151")
    ap.add_argument("--health-port", type=int, default=0,
                    help="serve /healthz /readyz /livez /metrics (0 = off)")
    args = ap.parse_args()
    srv = TPUScoreServer(args.listen)
    port = srv.start()
    if args.health_port:
        hs = HealthServer(f"127.0.0.1:{args.health_port}",
                          metrics=srv.engine.metrics,
                          ready_check=lambda: srv.engine.ready)
        print(f"health endpoints on port {hs.start()}")
    print(f"tpuscore sidecar listening on port {port}")
    threading.Event().wait()


if __name__ == "__main__":
    main()
