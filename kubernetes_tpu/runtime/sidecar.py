"""TPUScore gRPC sidecar — L4.

The JAX process that owns the TPU: receives activeQ + NodeInfo snapshots over
gRPC, runs the batched filter/score/commit kernels, streams binding verdicts
back.  Single-writer by construction: one server thread owns the device
(SURVEY.md §5 race-detection note — design the host side single-writer),
gRPC concurrency is serialized through a lock rather than locks in the engine.

Crash-only: the server keeps no state a reconnecting client cannot re-send —
every request carries the full snapshot (delta streaming is a planned
optimization; the contract already allows it because verdicts are pure
functions of the snapshot).

Service stubs are hand-wired with grpc.method_handlers_generic_handler (the
image has grpcio but not grpc_tools' codegen plugin).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from . import tpuscore_pb2 as pb
from .convert import snapshot_from_proto

SERVICE = "tpuscore.TPUScore"


class _Engine:
    """The in-process scheduling engine the server fronts."""

    def __init__(self):
        self._lock = threading.Lock()

    def schedule(self, snap, gang: bool, hard_pod_affinity_weight: float = 1.0):
        from ..api.snapshot import encode_snapshot
        from ..ops import schedule_batch
        from ..ops.gang import schedule_with_gangs
        from ..ops.scores import DEFAULT_SCORE_CONFIG, infer_score_config

        with self._lock:  # single writer on the device
            # the weight applies in BOTH stages: pre-bound pods at encode
            # time, batch-committed pods through the kernel config
            arr, meta = encode_snapshot(
                snap, hard_pod_affinity_weight=hard_pod_affinity_weight
            )
            base = dataclasses.replace(
                DEFAULT_SCORE_CONFIG, hard_pod_affinity_weight=hard_pod_affinity_weight
            )
            cfg = infer_score_config(arr, base)
            if gang:
                choices, _ = schedule_with_gangs(arr, cfg)
            else:
                choices = np.asarray(schedule_batch(arr, cfg)[0])
            return choices, meta


class TPUScoreServer:
    def __init__(self, address: str = "127.0.0.1:0", engine: Optional[_Engine] = None):
        self.engine = engine or _Engine()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        handlers = {
            "Schedule": grpc.unary_unary_rpc_method_handler(
                self._schedule,
                request_deserializer=pb.ScheduleRequest.FromString,
                response_serializer=pb.ScheduleResponse.SerializeToString,
            ),
            "Health": grpc.unary_unary_rpc_method_handler(
                self._health,
                request_deserializer=pb.HealthRequest.FromString,
                response_serializer=pb.HealthResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(address)

    # --- RPCs ---
    def _schedule(self, request: pb.ScheduleRequest, context) -> pb.ScheduleResponse:
        t0 = time.perf_counter()
        snap = snapshot_from_proto(request.snapshot)
        uid_of = {p.name: p.uid for p in snap.pending_pods}
        hpaw = (
            request.hard_pod_affinity_weight
            if request.HasField("hard_pod_affinity_weight")
            else 1.0
        )
        choices, meta = self.engine.schedule(snap, request.gang, hpaw)
        resp = pb.ScheduleResponse()
        for k in range(meta.n_pods):
            c = int(choices[k])
            name = meta.pod_names[k]
            resp.verdicts.append(
                pb.Verdict(
                    pod_uid=uid_of[name],
                    node=meta.node_names[c] if c >= 0 else "",
                    scheduled=c >= 0,
                )
            )
        resp.elapsed_ms = (time.perf_counter() - t0) * 1e3
        return resp

    def _health(self, request, context) -> pb.HealthResponse:
        import jax

        devs = jax.devices()
        return pb.HealthResponse(ok=True, platform=devs[0].platform, device_count=len(devs))

    # --- lifecycle ---
    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class HealthServer:
    """component-base health + metrics endpoints: /healthz /readyz /livez
    (apiserver/pkg/server/healthz) and a Prometheus-text /metrics —
    "every binary serves /metrics, /healthz|readyz|livez" (SURVEY.md §5)."""

    def __init__(self, address: str = "127.0.0.1:0", metrics=None,
                 ready_check=None):
        import http.server

        self.metrics = metrics
        self.ready_check = ready_check or (lambda: True)
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path in ("/healthz", "/livez"):
                    body, code = b"ok", 200
                elif self.path == "/readyz":
                    ok = outer.ready_check()
                    body, code = (b"ok", 200) if ok else (b"not ready", 503)
                elif self.path == "/metrics":
                    body, code = outer._render_metrics().encode(), 200
                else:
                    body, code = b"not found", 404
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        host, _, port = address.partition(":")
        self._httpd = http.server.HTTPServer((host, int(port or 0)), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    def _render_metrics(self) -> str:
        lines = []
        if self.metrics is not None:
            counters, gauges, hists = self.metrics.snapshot()
            for name, v in sorted(counters.items()):
                lines.append(f"# TYPE {name} counter\n{name} {v}")
            for name, v in sorted(gauges.items()):
                lines.append(f"# TYPE {name} gauge\n{name} {v}")
            for name, (p50, p99, count) in sorted(hists.items()):
                lines.append(
                    f"# TYPE {name} summary\n"
                    f"{name}{{quantile=\"0.5\"}} {p50}\n"
                    f"{name}{{quantile=\"0.99\"}} {p99}\n"
                    f"{name}_count {count}"
                )
        return "\n".join(lines) + "\n"

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()


def main() -> None:  # pragma: no cover - manual entry point
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--listen", default="127.0.0.1:50151")
    ap.add_argument("--health-port", type=int, default=0,
                    help="serve /healthz /readyz /livez /metrics (0 = off)")
    args = ap.parse_args()
    srv = TPUScoreServer(args.listen)
    port = srv.start()
    if args.health_port:
        hs = HealthServer(f"127.0.0.1:{args.health_port}",
                          ready_check=lambda: True)
        print(f"health endpoints on port {hs.start()}")
    print(f"tpuscore sidecar listening on port {port}")
    threading.Event().wait()


if __name__ == "__main__":
    main()
