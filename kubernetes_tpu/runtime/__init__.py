from .sidecar import TPUScoreServer  # noqa: F401
from .client import TPUScoreClient, SidecarUnavailable  # noqa: F401
