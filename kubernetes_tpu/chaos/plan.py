"""Deterministic fault injection — the chaos plan and its injector.

Borg/Omega-style schedulers treat machine and agent failure as the common
case (PAPERS.md); Kubernetes' own credibility rests on every component being
retried-with-backoff and crash-consistent.  PR 2 made the hot path fast by
making it fragile: deferred bind commits ride in the next cycle's device
window, donated buffers are invalidated mid-wave, and the sidecar hop is
crash-only reconnect.  This module makes those failure paths TESTABLE: a
seeded `FaultPlan` names which invocation of which hook site fails and how,
and the `ChaosInjector` fires it deterministically — so a chaos parity suite
can assert that under ANY injected plan the final placements are
bit-identical to the fault-free serial oracle (tests/test_chaos.py).

Hook sites (threaded through the components that own them):

  sidecar.rpc      runtime/client.py — the Schedule RPC: drop (error), hang
                   (sleep then error), partial (truncated response)
  sidecar.health   runtime/client.py — the Health RPC: drop
  pipeline.step    parallel/pipeline.py — the device-step fetch: exception
                   mid-wave (error) or poisoned verdicts (nan)
  scheduler.step   scheduler/scheduler.py — the batch kernel: same two
  compile.cache    ops/aot.py — corrupt a persistent-cache entry before the
                   AOT warmup loads it
  host.stall       scheduler/scheduler.py — a slow-host stall inside the
                   encode window (sleep only; nothing should break)
  kubelet.sync     scheduler/kubelet.py — a crash inside a pod worker's sync

Every fired fault emits a `fault_injected` span + a
`framework_fault_injected_total{site,action}` counter; every recovery the
components perform emits a `recovery` span + a
`framework_fault_recovery_total{site,action}` counter (record_recovery) —
the observability contract the acceptance criteria assert on.

Knobs: KTPU_CHAOS_SEED=<int> installs FaultPlan.from_seed(seed);
KTPU_FAULT_PLAN="site:action@at[:param];..." installs an explicit plan
(`@*` = every invocation).  `bench.harness --chaos <seed>` does the same and
reports recovery counts so BENCH runs can price recovery overhead.
"""

from __future__ import annotations

import contextlib
import os
import random
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np
from ..analysis.lockcheck import make_lock

# site -> the actions a seeded plan may draw for it
SITE_ACTIONS: Dict[str, Tuple[str, ...]] = {
    "sidecar.rpc": ("error", "hang", "partial"),
    "sidecar.health": ("error",),
    "pipeline.step": ("error", "nan"),
    "scheduler.step": ("error", "nan"),
    "compile.cache": ("corrupt",),
    "host.stall": ("stall",),
    "kubelet.sync": ("crash",),
    # process-death kill points (scheduler.kill family): a `kill` action
    # simulates kill -9 at an enumerated point of the bind path — it raises
    # ProcessKilled (BaseException: no component's Exception-level recovery
    # may "handle" it) and latches the module-wide killed() flag so the dead
    # instance's finally-blocks do nothing a SIGKILL'd process couldn't.
    # Recovery is a RESTART: scheduler.restart_scheduler builds a fresh
    # Scheduler on the surviving store and replays the checkpoint.
    "kill.post_assume": ("kill",),      # post cache.assume, pre checkpoint
    "kill.post_checkpoint": ("kill",),  # checkpoint durable, bind unpublished
    "kill.mid_flush": ("kill",),        # mid deferred-commit flush fan-out
    "kill.mid_step": ("kill",),         # mid device step, donated bufs in flight
    # the STREAMING kill family (parallel/pipeline.py): death points of the
    # pipelined loop itself.  Recovery is pipeline.run_stream_restartable —
    # a fresh loop replaying every wave the stream WAL has not committed.
    "kill.submit": ("kill",),           # wave accepted, nothing dispatched
    "kill.dispatch": ("kill",),         # dispatched, donated bufs in flight
    "kill.collect": ("kill",),          # verdicts fetched but uncommitted
    "kill.drain": ("kill",),            # final in-flight wave unharvested
}

# the kill-point family: excluded from seeded storms UNLESS explicitly
# requested (sites=) — a kill is only recoverable by a caller running the
# crash-restart protocol, and pre-existing seeds must keep producing the
# exact same plans (same seed -> same plan, bit for bit)
KILL_SITES: Tuple[str, ...] = (
    "kill.post_assume", "kill.post_checkpoint", "kill.mid_flush",
    "kill.mid_step",
)

# the streaming loop's kill points, a SEPARATE tuple on purpose: existing
# seeded storms and parity tests draw from KILL_SITES (from_seed(seed,
# sites=KILL_SITES) must keep producing identical plans), so new sites may
# only ever extend the site table at the end, never reshuffle that tuple
STREAM_KILL_SITES: Tuple[str, ...] = (
    "kill.submit", "kill.dispatch", "kill.collect", "kill.drain",
)

# every process-death site (what "has a kill been armed?" checks should use)
ALL_KILL_SITES: Tuple[str, ...] = KILL_SITES + STREAM_KILL_SITES

ALWAYS = -1  # Fault.at sentinel: fire on every invocation of the site


class FaultInjected(RuntimeError):
    """Raised by the injector for error/hang/crash actions; components treat
    it exactly like the organic failure it stands in for (an RpcError, an
    XLA runtime error, a plugin bug)."""

    def __init__(self, fault: "Fault"):
        super().__init__(f"injected fault {fault.site}:{fault.action}@{fault.at}")
        self.fault = fault


class ProcessKilled(BaseException):
    """Simulated kill -9 at an enumerated kill point.

    Deliberately a BaseException: every in-process recovery path catches
    Exception, and a SIGKILL'd process gets no chance to recover, flush or
    clean up — the only legitimate response is a restart from checkpoint +
    LIST/WATCH (scheduler.restart_scheduler).  The injector latches the
    module-wide killed() flag BEFORE raising so the dying instance's
    finally-blocks (deferred-bind flush, pipeline drain) see the process as
    dead and do nothing; the restart driver calls revive() once the
    replacement is constructed."""

    def __init__(self, fault: "Fault"):
        super().__init__(f"process killed at {fault.site}@{fault.at}")
        self.fault = fault


@dataclass(frozen=True)
class Fault:
    site: str
    action: str
    at: int = 0        # fires on invocations [at, at+count) of the site; ALWAYS = every one
    count: int = 1
    param: float = 0.0  # hang/stall seconds

    def spec(self) -> str:
        at = "*" if self.at == ALWAYS else (
            str(self.at) if self.count == 1 else f"{self.at}+{self.count}"
        )
        s = f"{self.site}:{self.action}@{at}"
        if self.param:
            s += f":{self.param}"
        return s

    def covers(self, n: int) -> bool:
        return self.at == ALWAYS or self.at <= n < self.at + self.count


class FaultPlan:
    """An ordered set of faults; first match per (site, invocation) wins."""

    def __init__(self, faults, seed: Optional[int] = None):
        self.faults: Tuple[Fault, ...] = tuple(faults)
        self.seed = seed
        for f in self.faults:
            if f.site not in SITE_ACTIONS:
                raise ValueError(f"unknown chaos site {f.site!r}")
            if f.action not in SITE_ACTIONS[f.site]:
                raise ValueError(
                    f"site {f.site!r} does not support action {f.action!r}"
                )

    def describe(self) -> str:
        head = f"seed={self.seed} " if self.seed is not None else ""
        return head + ";".join(f.spec() for f in self.faults)

    def match(self, site: str, n: int) -> Optional[Fault]:
        for f in self.faults:
            if f.site == site and f.covers(n):
                return f
        return None

    @classmethod
    def single(cls, site: str, action: str, at: int = 0, count: int = 1,
               param: float = 0.0) -> "FaultPlan":
        return cls([Fault(site, action, at, count, param)])

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """"site:action@at[:param];..." — `@*` fires every invocation,
        `@a+k` fires k consecutive invocations starting at a."""
        faults: List[Fault] = []
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            site, _, rest = part.partition(":")
            action, _, where = rest.partition("@")
            if not site or not action or not where:
                raise ValueError(f"bad fault spec {part!r} "
                                 "(want site:action@at[:param])")
            where, _, param = where.partition(":")
            if where == "*":
                at, count = ALWAYS, 1
            elif "+" in where:
                a, _, k = where.partition("+")
                at, count = int(a), int(k)
            else:
                at, count = int(where), 1
            faults.append(Fault(site.strip(), action.strip(), at, count,
                                float(param) if param else 0.0))
        return cls(faults)

    @classmethod
    def from_seed(cls, seed: int, n_faults: int = 8,
                  sites: Optional[Tuple[str, ...]] = None,
                  horizon: int = 12) -> "FaultPlan":
        """A deterministic storm: n_faults draws of (site, action, ordinal)
        over the first `horizon` invocations of each site.  Same seed ->
        same plan, bit for bit — replaying a failing seed reproduces the
        exact fault sequence.

        The default pool excludes the kill.* sites: a kill is recoverable
        only by a caller running the crash-restart protocol, and existing
        seeds must keep producing identical plans.  Pass sites= (e.g. from
        sites_matching("kill.*")) to storm the kill points."""
        rng = random.Random(seed)
        pool = tuple(sites) if sites else tuple(
            s for s in SITE_ACTIONS if s not in ALL_KILL_SITES
        )
        faults = []
        for _ in range(n_faults):
            site = pool[rng.randrange(len(pool))]
            actions = SITE_ACTIONS[site]
            action = actions[rng.randrange(len(actions))]
            param = round(rng.uniform(0.005, 0.03), 4) if action in (
                "hang", "stall"
            ) else 0.0
            faults.append(Fault(site, action, rng.randrange(horizon),
                                param=param))
        return cls(faults, seed=seed)


def sites_matching(pattern: str) -> Tuple[str, ...]:
    """Resolve a comma-separated fnmatch glob list against the site table
    (`bench.harness --chaos-sites`).  A `!glob` term excludes; with only
    exclusions the include set defaults to every site.  Examples:
    "kill.*" -> just the kill points; "*,!kill.*" -> everything else;
    "scheduler.*,kill.mid_flush" -> a targeted mix."""
    from fnmatch import fnmatchcase

    include: List[str] = []
    exclude: List[str] = []
    for p in pattern.split(","):
        p = p.strip()
        if not p:
            continue
        (exclude if p.startswith("!") else include).append(p.lstrip("!"))
    if not include:
        include = ["*"]
    return tuple(
        s for s in SITE_ACTIONS
        if any(fnmatchcase(s, p) for p in include)
        and not any(fnmatchcase(s, p) for p in exclude)
    )


class ChaosInjector:
    """Counts invocations per site and fires the plan's matching fault.

    Faults and recoveries are double-booked: on the injector's own Metrics
    (the process-wide chaos ledger the harness reports) and, when the
    calling component passes its Metrics/Tracer, on those too — so
    `framework_fault_recovery_total{site,action}` shows up next to the
    scheduler's ordinary series and the spans land in whatever collector
    the run exports."""

    def __init__(self, plan: FaultPlan, metrics=None, tracer=None):
        from ..scheduler.metrics import Metrics
        from ..scheduler.tracing import Tracer

        self.plan = plan
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer if tracer is not None else Tracer(component="chaos")
        self._lock = make_lock("ChaosInjector._lock")
        self.counts: Dict[str, int] = {}

    def poke(self, site: str, tracer=None, metrics=None, **attrs) -> Optional[Fault]:
        """One invocation of `site`.  Returns None when nothing fires.  For
        error/hang/crash the matching FaultInjected is RAISED (hang sleeps
        param first); stall sleeps and returns the fault; data faults
        (nan/partial/corrupt) are returned for the caller to apply."""
        with self._lock:
            n = self.counts.get(site, 0)
            self.counts[site] = n + 1
        f = self.plan.match(site, n)
        if f is None:
            return None
        self._mark("fault_injected", "framework_fault_injected_total",
                   f, tracer, metrics, invocation=n, **attrs)
        if f.action == "kill":
            # latch BEFORE raising: the dying instance's unwind (finally
            # blocks included) must observe killed() and do nothing
            global _KILLED
            _KILLED = True
            raise ProcessKilled(f)
        if f.action in ("hang", "stall"):
            time.sleep(f.param or 0.01)
        if f.action in ("error", "hang", "crash"):
            raise FaultInjected(f)
        return f

    def _mark(self, span_name: str, counter: str, f: Fault, tracer, metrics,
              **attrs) -> None:
        now = time.perf_counter()
        for tr in {id(t): t for t in (tracer, self.tracer) if t is not None}.values():
            if tr.enabled:
                tr.record_span(span_name, start=now, end=now, site=f.site,
                               action=f.action, **attrs)
        for m in {id(m): m for m in (metrics, self.metrics) if m is not None}.values():
            m.inc_labeled(counter, site=f.site, action=f.action)

    def record_recovery(self, site: str, action: str, tracer=None,
                        metrics=None, start: Optional[float] = None,
                        **attrs) -> None:
        now = time.perf_counter()
        t0 = start if start is not None else now
        for tr in {id(t): t for t in (tracer, self.tracer) if t is not None}.values():
            if tr.enabled:
                tr.record_span("recovery", start=t0, end=now, site=site,
                               action=action, **attrs)
        for m in {id(m): m for m in (metrics, self.metrics) if m is not None}.values():
            m.inc_labeled("framework_fault_recovery_total",
                          site=site, action=action)

    def report(self) -> Dict[str, float]:
        """Injected/recovered counters for bench artifacts."""
        with self.metrics._lock:
            counters = {
                name + self.metrics.render_labels(key): v
                for name, series in self.metrics.labeled_counters.items()
                for key, v in series.items()
            }
        counters["chaos_sites_poked"] = float(sum(self.counts.values()))
        return counters


# --- the process-wide injector (None = chaos off; the poke fast path is one
# global read, so the disabled hot-path cost is a dict lookup away from zero)
_ACTIVE: Optional[ChaosInjector] = None
_FALLBACK_METRICS = None  # recoveries from ORGANIC faults still count
# the kill latch: True from the instant a kill fault fires until the restart
# driver revives — components' drain/flush/cleanup paths check killed() so a
# dead instance's finally-blocks do nothing a SIGKILL'd process couldn't
_KILLED = False


def killed() -> bool:
    """True while the simulated process is dead (a kill fault fired and no
    restart has revived it)."""
    return _KILLED


def revive() -> None:
    """Clear the kill latch — the restart driver's first act, called once
    the replacement scheduler is about to be constructed."""
    global _KILLED
    _KILLED = False


def install(plan: FaultPlan, metrics=None, tracer=None) -> ChaosInjector:
    global _ACTIVE
    _ACTIVE = ChaosInjector(plan, metrics=metrics, tracer=tracer)
    return _ACTIVE


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None
    revive()  # a leaked kill latch must not outlive the plan (test hygiene)


def active() -> Optional[ChaosInjector]:
    return _ACTIVE


def enabled() -> bool:
    return _ACTIVE is not None


def poke(site: str, tracer=None, metrics=None, **attrs) -> Optional[Fault]:
    """The component-side hook: no-op (None) unless a plan is installed."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj.poke(site, tracer=tracer, metrics=metrics, **attrs)


def record_recovery(site: str, action: str, tracer=None, metrics=None,
                    start: Optional[float] = None, **attrs) -> None:
    """Recovery accounting — works with chaos OFF too (organic faults):
    the span lands on the caller's tracer and the counter on the caller's
    metrics plus the module ledger."""
    inj = _ACTIVE
    if inj is not None:
        inj.record_recovery(site, action, tracer=tracer, metrics=metrics,
                            start=start, **attrs)
        return
    global _FALLBACK_METRICS
    if _FALLBACK_METRICS is None:
        from ..scheduler.metrics import Metrics

        _FALLBACK_METRICS = Metrics()
    now = time.perf_counter()
    if tracer is not None and tracer.enabled:
        tracer.record_span("recovery", start=start if start is not None else now,
                           end=now, site=site, action=action, **attrs)
    for m in {id(m): m for m in (metrics, _FALLBACK_METRICS) if m is not None}.values():
        m.inc_labeled("framework_fault_recovery_total", site=site, action=action)


def maybe_install_from_env() -> Optional[ChaosInjector]:
    """KTPU_FAULT_PLAN (explicit spec) wins over KTPU_CHAOS_SEED (seeded
    storm).  Idempotent: an already-installed injector is kept."""
    if _ACTIVE is not None:
        return _ACTIVE
    spec = os.environ.get("KTPU_FAULT_PLAN")
    if spec:
        return install(FaultPlan.parse(spec))
    seed = os.environ.get("KTPU_CHAOS_SEED")
    if seed:
        return install(FaultPlan.from_seed(int(seed)))
    return None


@contextlib.contextmanager
def chaos_plan(plan: FaultPlan, metrics=None, tracer=None):
    """Scoped install for tests: always uninstalls, even on failure."""
    inj = install(plan, metrics=metrics, tracer=tracer)
    try:
        yield inj
    finally:
        uninstall()


# --- shared verdict validation (the NaN-verdict recovery gate) ---
def poisoned_verdicts(choices, n_nodes: int) -> bool:
    """True when a fetched choices vector cannot be decoded: non-finite
    entries (a NaN verdict), or indices outside [-1, n_nodes) (garbage from
    a corrupted readback).  The decode paths check this BEFORE indexing
    node_names, so a poisoned wave routes to the serial-oracle replay
    instead of crashing (or silently binding pods to the wrong node)."""
    ch = np.asarray(choices)
    if ch.size == 0:
        return False
    if np.issubdtype(ch.dtype, np.floating):
        if not bool(np.all(np.isfinite(ch))):
            return True
        ch = ch.astype(np.int64)
    elif not np.issubdtype(ch.dtype, np.integer):
        return True
    return bool(np.any((ch < -1) | (ch >= n_nodes)))


def poison(choices) -> np.ndarray:
    """The nan-action payload: a float copy with every 7th entry NaN —
    what a corrupted device readback looks like to the decode path."""
    ch = np.asarray(choices).astype(np.float64).copy()
    ch[:: 7] = np.nan
    return ch


class PoisonedWave(RuntimeError):
    """A wave whose verdicts failed poisoned_verdicts — recoverable by the
    serial-oracle replay, never by decoding as-is."""
