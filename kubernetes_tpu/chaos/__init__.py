"""Deterministic fault-injection + crash-safe recovery plumbing (see plan.py)."""

from .plan import (  # noqa: F401
    ALWAYS,
    ChaosInjector,
    Fault,
    FaultInjected,
    FaultPlan,
    PoisonedWave,
    SITE_ACTIONS,
    active,
    chaos_plan,
    enabled,
    install,
    maybe_install_from_env,
    poison,
    poisoned_verdicts,
    poke,
    record_recovery,
    uninstall,
)
