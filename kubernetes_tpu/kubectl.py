"""kubectl analog — the CLI/UX layer (SURVEY.md §1 layer 9).

reference: staging/src/k8s.io/kubectl/pkg/cmd/ — each verb is a cobra command
built on client-go.  Here each verb is a method on `Kubectl`, built on the
in-process APIServer facade (the full handler chain: authn → APF → RBAC →
admission → registry), so CLI requests are subject to the same security and
fair-queuing path as any other client.  `main()` wires a standalone in-process
cluster from manifest files for demo use; tests and the harness construct
`Kubectl` directly around a live cluster.

Implemented verbs (reference file in kubectl/pkg/cmd/<verb>/):
get, describe, apply, create, delete, scale, label, taint, cordon, uncordon,
drain (PDB-respecting eviction — the Eviction subresource's check), top,
rollout status, api-resources, auth can-i, events, version.
"""

from __future__ import annotations

import copy
import io
import shlex
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .api import cluster as c
from .api import serialize as ser
from .api import types as t
from .scheduler.apiserver import APIServer, resource_of
from .scheduler.disruption import DisruptionController
from .scheduler.events import EventRecorder
from .scheduler.store import ClusterStore


class KubectlError(Exception):
    """Command failure; message is the user-facing error line."""


# word (plural/singular/shortname) -> store kind
_KIND_WORDS: Dict[str, str] = {}


def _register_words(kind: str, *words: str) -> None:
    for w in words:
        _KIND_WORDS[w.lower()] = kind


_register_words("Pod", "pod", "pods", "po")
_register_words("Node", "node", "nodes", "no")
_register_words("PDB", "poddisruptionbudget", "poddisruptionbudgets", "pdb", "pdbs")
_register_words("ReplicaSet", "replicaset", "replicasets", "rs")
_register_words("Deployment", "deployment", "deployments", "deploy")
_register_words("Job", "job", "jobs")
_register_words("StatefulSet", "statefulset", "statefulsets", "sts")
_register_words("DaemonSet", "daemonset", "daemonsets", "ds")
_register_words("CronJob", "cronjob", "cronjobs", "cj")
_register_words("Service", "service", "services", "svc")
_register_words("EndpointSlice", "endpointslice", "endpointslices", "eps")
_register_words("Namespace", "namespace", "namespaces", "ns")
_register_words("PriorityClass", "priorityclass", "priorityclasses", "pc")
_register_words("ResourceQuota", "resourcequota", "resourcequotas", "quota")
_register_words("LimitRange", "limitrange", "limitranges", "limits")
_register_words(
    "HorizontalPodAutoscaler", "horizontalpodautoscaler", "horizontalpodautoscalers", "hpa"
)
_register_words("Role", "role", "roles", "clusterrole", "clusterroles")
_register_words("RoleBinding", "rolebinding", "rolebindings",
                "clusterrolebinding", "clusterrolebindings")
_register_words("CustomResourceDefinition", "customresourcedefinition",
                "customresourcedefinitions", "crd", "crds")
_register_words("PV", "persistentvolume", "persistentvolumes", "pv")
_register_words("PVC", "persistentvolumeclaim", "persistentvolumeclaims", "pvc")
_register_words("StorageClass", "storageclass", "storageclasses", "sc")
_register_words("ResourceSlice", "resourceslice", "resourceslices")
_register_words("DeviceClass", "deviceclass", "deviceclasses")
_register_words("ResourceClaim", "resourceclaim", "resourceclaims")
_register_words("CertificateSigningRequest", "certificatesigningrequest",
                "certificatesigningrequests", "csr", "csrs")
_register_words("Event", "event", "events", "ev")
_register_words("FlowSchema", "flowschema", "flowschemas")
_register_words("PriorityLevelConfiguration", "prioritylevelconfiguration",
                "prioritylevelconfigurations")

# serializer kind -> store kind where they differ
_STORE_KIND = {
    "PodDisruptionBudget": "PDB",
    "PersistentVolume": "PV",
    "PersistentVolumeClaim": "PVC",
}
# kinds with no namespace column
_CLUSTER_SCOPED = {"Node", "Namespace", "PriorityClass", "PV", "StorageClass",
                   "ResourceSlice", "DeviceClass", "FlowSchema",
                   "PriorityLevelConfiguration", "CustomResourceDefinition",
                   "CertificateSigningRequest"}


def _singular(resource: str) -> str:
    """storageclasses -> storageclass, pods -> pod (the kubectl name printer)."""
    if resource.endswith("classes"):
        return resource[:-2]
    return resource[:-1] if resource.endswith("s") else resource


def resolve_kind(word: str, api=None) -> str:
    k = _KIND_WORDS.get(word.lower())
    if k is None and api is not None:
        # dynamic discovery: established CustomResourceDefinitions serve their
        # plural / kind / full name as resource words (the reference's
        # RESTMapper consults discovery the same way)
        crds = getattr(api, "crds", None)
        if crds is not None:
            w = word.lower()
            for crd in crds._by_kind.values():
                if w in (crd.plural.lower(), crd.kind.lower(), crd.name.lower()):
                    return crd.kind
    if k is None:
        raise KubectlError(f'the server doesn\'t have a resource type "{word}"')
    return k


def _store_kind(obj: object) -> str:
    kind = ser.kind_of(obj)
    return _STORE_KIND.get(kind, kind)


def _fmt_table(headers: Sequence[str], rows: Sequence[Sequence[str]]) -> str:
    if not rows:
        return "No resources found.\n"
    cols = [headers, *[[str(v) for v in r] for r in rows]]
    widths = [max(len(r[i]) for r in cols) for i in range(len(headers))]
    lines = [
        "   ".join(v.ljust(w) for v, w in zip(r, widths)).rstrip() for r in cols
    ]
    return "\n".join(lines) + "\n"


def _parse_flags(argv: List[str]) -> Tuple[List[str], Dict[str, object]]:
    """Split positional words from the small flag set kubectl verbs share."""
    pos: List[str] = []
    flags: Dict[str, object] = {}
    i = 0
    value_flags = {"-n": "namespace", "--namespace": "namespace",
                   "-o": "output", "--output": "output",
                   "-f": "filename", "--filename": "filename",
                   "-l": "selector", "--selector": "selector",
                   "--replicas": "replicas"}
    bool_flags = {"-A": "all_namespaces", "--all-namespaces": "all_namespaces",
                  "--force": "force", "--overwrite": "overwrite",
                  "--disable-eviction": "disable_eviction",
                  "--ignore-daemonsets": "ignore_daemonsets"}
    while i < len(argv):
        a = argv[i]
        if "=" in a and a.split("=", 1)[0] in value_flags:
            k, v = a.split("=", 1)
            flags[value_flags[k]] = v
        elif a in value_flags:
            if i + 1 >= len(argv):
                raise KubectlError(f"flag {a} needs a value")
            flags[value_flags[a]] = argv[i + 1]
            i += 1
        elif a in bool_flags:
            flags[bool_flags[a]] = True
        else:
            pos.append(a)
        i += 1
    return pos, flags


class Kubectl:
    def __init__(
        self,
        api: APIServer,
        token: str,
        recorder: Optional[EventRecorder] = None,
    ):
        self.api = api
        self.token = token
        self.recorder = recorder

    # ------------------------------------------------------------- dispatch
    def run(self, command) -> str:
        """Run one command (string or argv list) → its stdout text.
        Raises KubectlError with the user-facing message on failure."""
        argv = shlex.split(command) if isinstance(command, str) else list(command)
        if not argv:
            raise KubectlError("no command given")
        verb, rest = argv[0], argv[1:]
        handler = getattr(self, f"_cmd_{verb.replace('-', '_')}", None)
        if handler is None:
            raise KubectlError(f'unknown command "{verb}"')
        pos, flags = _parse_flags(rest)
        return handler(pos, flags)

    # ------------------------------------------------------------- helpers
    def _handle(self, verb: str, kind: str, obj=None, namespace="", name=""):
        from .scheduler.admission import AdmissionDenied
        from .scheduler.apiserver import Forbidden, Unauthenticated
        from .scheduler.flowcontrol import RequestRejected

        try:
            return self.api.handle(self.token, verb, kind, obj,
                                   namespace=namespace, name=name)
        except (Unauthenticated, Forbidden, AdmissionDenied, RequestRejected) as e:
            raise KubectlError(f"Error from server: {e}") from None

    def _ns(self, flags) -> Optional[str]:
        if flags.get("all_namespaces"):
            return None
        return flags.get("namespace", "default")

    def _get_required(self, kind: str, ns: str, name: str):
        obj = self._handle("get", kind, namespace=ns if kind not in _CLUSTER_SCOPED else "",
                           name=name)
        if obj is None:
            nsmsg = f' in namespace "{ns}"' if kind not in _CLUSTER_SCOPED else ""
            raise KubectlError(
                f'Error from server (NotFound): {resource_of(kind)} "{name}" not found{nsmsg}'
            )
        return obj

    # ------------------------------------------------------------------ get
    def _cmd_get(self, pos, flags):
        if not pos:
            raise KubectlError("get needs a resource type")
        kind = resolve_kind(pos[0], self.api)
        ns = self._ns(flags) if kind not in _CLUSTER_SCOPED else None
        if len(pos) > 1:
            objs = [self._get_required(kind, ns or "default", pos[1])]
        else:
            objs = list(self._handle("list", kind, namespace=ns or ""))
            if ns is not None and kind not in _CLUSTER_SCOPED:
                objs = [o for o in objs if getattr(o, "namespace", ns) == ns]
        sel = flags.get("selector")
        if sel:
            # key=value equality and bare-key existence terms, comma-ANDed
            def _matches(o) -> bool:
                labels = getattr(o, "labels", {})
                for term in sel.split(","):
                    if "=" in term:
                        k, v = term.split("=", 1)
                        if labels.get(k) != v:
                            return False
                    elif term not in labels:
                        return False
                return True

            objs = [o for o in objs if _matches(o)]
        out = flags.get("output", "")
        if out == "yaml":
            return ser.dump_yaml(objs if len(objs) != 1 else objs[0])
        if out == "json":
            import json

            docs = [ser.to_manifest(o) for o in objs]
            return json.dumps(docs[0] if len(docs) == 1 else
                              {"kind": "List", "items": docs}, indent=2) + "\n"
        if out == "name":
            return "".join(
                f"{_singular(resource_of(kind))}"
                f"/{o.name}\n" for o in objs)
        return self._table(kind, objs, wide=out == "wide")

    def _table(self, kind: str, objs, wide: bool = False) -> str:
        rows = []
        if kind == "Pod":
            headers = ["NAME", "STATUS", "NODE", "PRIORITY"]
            if wide:
                headers += ["IP", "NOMINATED"]
            for p in objs:
                status = p.phase or ("Running" if p.node_name else "Pending")
                r = [p.name, status, p.node_name or "<none>", p.priority]
                if wide:
                    r += [p.pod_ip or "<none>", p.nominated_node_name or "<none>"]
                rows.append(r)
            return _fmt_table(headers, rows)
        if kind == "Node":
            headers = ["NAME", "STATUS", "TAINTS", "CPU", "MEMORY"]
            for n in objs:
                status = "Ready,SchedulingDisabled" if n.unschedulable else "Ready"
                rows.append([n.name, status, len(n.taints),
                             n.allocatable.get("cpu", 0), n.allocatable.get("memory", 0)])
            return _fmt_table(headers, rows)
        if kind in ("ReplicaSet", "StatefulSet"):
            return _fmt_table(
                ["NAME", "DESIRED", "READY"],
                [[o.name, o.replicas, o.ready_replicas] for o in objs])
        if kind == "Deployment":
            store = self.api.store
            for d in objs:
                ready = sum(
                    rs.ready_replicas for rs in store.list_objects("ReplicaSet")
                    if any(ref.uid == d.uid for ref in rs.owner_references))
                rows.append([d.name, f"{ready}/{d.replicas}"])
            return _fmt_table(["NAME", "READY"], rows)
        if kind == "Job":
            return _fmt_table(
                ["NAME", "COMPLETIONS", "ACTIVE"],
                [[j.name, f"{j.succeeded}/{j.completions}", j.active] for j in objs])
        if kind == "Service":
            return _fmt_table(
                ["NAME", "CLUSTER-IP", "PORTS"],
                [[s.name, s.cluster_ip or "<none>",
                  ",".join(f"{p.port}/{p.protocol}" for p in s.ports) or "<none>"]
                 for s in objs])
        if kind == "PDB":
            return _fmt_table(
                ["NAME", "MIN-AVAILABLE", "MAX-UNAVAILABLE", "ALLOWED"],
                [[p.name,
                  p.min_available if p.min_available is not None else "N/A",
                  p.max_unavailable if p.max_unavailable is not None else "N/A",
                  p.disruptions_allowed] for p in objs])
        if kind == "PV":
            return _fmt_table(
                ["NAME", "CAPACITY", "STORAGECLASS", "CLAIM"],
                [[v.name, v.capacity, v.storage_class or "<none>",
                  v.claim_ref or "<unbound>"] for v in objs])
        if kind == "PVC":
            return _fmt_table(
                ["NAME", "STATUS", "VOLUME", "STORAGECLASS"],
                [[v.name, "Bound" if v.volume_name else "Pending",
                  v.volume_name or "<none>", v.storage_class or "<none>"] for v in objs])
        if kind == "Event":
            return _fmt_table(
                ["LAST SEEN", "COUNT", "REASON", "OBJECT", "NODE", "MESSAGE"],
                [[f"{e.last_seen:.0f}", e.count, e.reason, e.involved_object,
                  e.node or "", e.message]
                 for e in sorted(objs, key=lambda e: e.last_seen)])
        # generic fallback: NAME (+NAMESPACE)
        if kind in _CLUSTER_SCOPED:
            return _fmt_table(["NAME"], [[o.name] for o in objs])
        return _fmt_table(["NAMESPACE", "NAME"],
                          [[getattr(o, "namespace", ""), o.name] for o in objs])

    # ------------------------------------------------------------- describe
    def _cmd_describe(self, pos, flags):
        if len(pos) < 2:
            raise KubectlError("describe needs a resource type and a name")
        kind = resolve_kind(pos[0], self.api)
        ns = self._ns(flags) or "default"
        obj = self._get_required(kind, ns, pos[1])
        buf = io.StringIO()
        buf.write(f"Name:         {obj.name}\n")
        if kind not in _CLUSTER_SCOPED:
            buf.write(f"Namespace:    {getattr(obj, 'namespace', '')}\n")
        labels = getattr(obj, "labels", None)
        if labels is not None:
            buf.write("Labels:       "
                      + (",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                         or "<none>") + "\n")
        body = ser.to_plain(obj)
        for skip in ("name", "namespace", "labels", "uid"):
            body.pop(skip, None)
        import yaml as _yaml

        if body:
            buf.write(_yaml.safe_dump(body, sort_keys=False, default_flow_style=None))
        if kind == "Pod" and self.recorder is not None:
            evs = [e for e in self.recorder.events if e.pod == obj.uid]
            if evs:
                buf.write("Events:\n")
                for e in evs[-10:]:
                    # reason + node + message (the diagnosis plane's
                    # "0/N nodes are available: …" lands here — the
                    # `kubectl describe pod` surface operators grep)
                    detail = "\t".join(x for x in (e.node, e.message) if x)
                    buf.write(f"  {e.reason}\t{detail}\n")
        return buf.getvalue()

    # --------------------------------------------------------- apply/create
    def _load_filename(self, flags) -> list:
        fn = flags.get("filename")
        if not fn:
            raise KubectlError("must specify -f")
        if fn == "-":
            text = sys.stdin.read()
        else:
            try:
                with open(fn) as fh:
                    text = fh.read()
            except OSError as e:
                raise KubectlError(str(e)) from None
        try:
            return ser.load_yaml(text)
        except ser.DecodeError as e:
            raise KubectlError(f"error decoding {fn}: {e}") from None

    def _cmd_apply(self, pos, flags):
        lines = []
        for obj in self._load_filename(flags):
            kind = _store_kind(obj)
            ns = getattr(obj, "namespace", "")
            existing = self._handle("get", kind, namespace=ns, name=obj.name)
            verb = "update" if existing is not None else "create"
            self._handle(verb, kind, obj)
            what = "configured" if verb == "update" else "created"
            lines.append(f"{_singular(resource_of(kind))}/{obj.name} {what}\n")
        return "".join(lines)

    def _cmd_create(self, pos, flags):
        lines = []
        for obj in self._load_filename(flags):
            kind = _store_kind(obj)
            ns = getattr(obj, "namespace", "")
            if self._handle("get", kind, namespace=ns, name=obj.name) is not None:
                raise KubectlError(
                    f'Error from server (AlreadyExists): {resource_of(kind)} '
                    f'"{obj.name}" already exists')
            self._handle("create", kind, obj)
            lines.append(f"{_singular(resource_of(kind))}/{obj.name} created\n")
        return "".join(lines)

    # --------------------------------------------------------------- delete
    def _cmd_delete(self, pos, flags):
        targets: List[Tuple[str, str, str]] = []  # (kind, ns, name)
        if flags.get("filename"):
            for obj in self._load_filename(flags):
                targets.append((_store_kind(obj), getattr(obj, "namespace", ""), obj.name))
        else:
            if len(pos) < 2:
                raise KubectlError("delete needs a resource type and a name")
            kind = resolve_kind(pos[0], self.api)
            ns = (self._ns(flags) or "default") if kind not in _CLUSTER_SCOPED else ""
            targets.extend((kind, ns, name) for name in pos[1:])
        lines = []
        for kind, ns, name in targets:
            self._get_required(kind, ns, name)
            self._handle("delete", kind, namespace=ns, name=name)
            lines.append(f'{_singular(resource_of(kind))} "{name}" deleted\n')
        return "".join(lines)

    # ---------------------------------------------------------------- scale
    def _cmd_scale(self, pos, flags):
        if "replicas" not in flags:
            raise KubectlError("scale needs --replicas=N")
        n = int(flags["replicas"])  # type: ignore[arg-type]
        if not pos:
            raise KubectlError("scale needs a resource (kind/name)")
        if "/" in pos[0]:
            kw, name = pos[0].split("/", 1)
        elif len(pos) >= 2:
            kw, name = pos[0], pos[1]
        else:
            raise KubectlError("scale needs a resource (kind/name)")
        kind = resolve_kind(kw, self.api)
        if kind not in ("Deployment", "ReplicaSet", "StatefulSet"):
            raise KubectlError(f"cannot scale {resource_of(kind)}")
        ns = self._ns(flags) or "default"
        obj = copy.copy(self._get_required(kind, ns, name))
        obj.replicas = n
        self._handle("update", kind, obj)
        return f"{_singular(resource_of(kind))}/{name} scaled\n"

    # ------------------------------------------------------ cordon / uncordon
    def _set_unschedulable(self, name: str, value: bool) -> str:
        node = copy.copy(self._get_required("Node", "", name))
        already = node.unschedulable == value
        if not already:
            node.unschedulable = value
            self._handle("update", "Node", node)
        verb = "cordoned" if value else "uncordoned"
        return f"node/{name} {'already ' if already else ''}{verb}\n"

    def _cmd_cordon(self, pos, flags):
        if not pos:
            raise KubectlError("cordon needs a node name")
        return self._set_unschedulable(pos[0], True)

    def _cmd_uncordon(self, pos, flags):
        if not pos:
            raise KubectlError("uncordon needs a node name")
        return self._set_unschedulable(pos[0], False)

    # ---------------------------------------------------------------- drain
    def _cmd_drain(self, pos, flags):
        """cordon + evict all non-DaemonSet pods, honoring PDBs — the
        Eviction subresource's disruptions_allowed check (reference:
        pkg/registry/core/pod/storage/eviction.go)."""
        if not pos:
            raise KubectlError("drain needs a node name")
        name = pos[0]
        out = [self._set_unschedulable(name, True)]
        store = self.api.store
        # fresh PDB status before charging evictions
        DisruptionController(store).tick()
        budgets = {p.key: copy.copy(p) for p in store.list_pdbs()}
        for pod in store.list_pods():
            if pod.node_name != name:
                continue
            if any(ref.kind == "DaemonSet" for ref in pod.owner_references):
                if flags.get("ignore_daemonsets"):
                    continue
                raise KubectlError(
                    f"cannot delete DaemonSet-managed pod {pod.name} "
                    "(use --ignore-daemonsets)")
            if not flags.get("disable_eviction"):
                blocking = [p for p in budgets.values() if p.matches(pod)]
                if any(b.disruptions_allowed <= 0 for b in blocking):
                    raise KubectlError(
                        f"Cannot evict pod {pod.name}: violates PodDisruptionBudget "
                        + ",".join(b.name for b in blocking
                                   if b.disruptions_allowed <= 0))
                for b in blocking:
                    b.disruptions_allowed -= 1
                    store.update_pdb(b)
            self._handle("delete", "Pod", namespace=pod.namespace, name=pod.name)
            out.append(f'pod "{pod.name}" evicted\n')
        out.append(f"node/{name} drained\n")
        return "".join(out)

    # ---------------------------------------------------------------- taint
    def _cmd_taint(self, pos, flags):
        if len(pos) < 3 or resolve_kind(pos[0], self.api) != "Node":
            raise KubectlError("usage: taint nodes <name> key=value:Effect | key[:Effect]-")
        name = pos[1]
        node = copy.copy(self._get_required("Node", "", name))
        taints = list(node.taints)
        for spec in pos[2:]:
            if spec.endswith("-"):  # removal
                body = spec[:-1]
                key, _, effect = body.partition(":")
                key = key.split("=", 1)[0]
                taints = [tn for tn in taints
                          if not (tn.key == key and (not effect or tn.effect == effect))]
            else:
                kv, _, effect = spec.partition(":")
                if not effect:
                    raise KubectlError(f"invalid taint spec {spec!r} (need key[=value]:Effect)")
                key, _, value = kv.partition("=")
                taints = [tn for tn in taints
                          if not (tn.key == key and tn.effect == effect)]
                taints.append(t.Taint(key=key, value=value, effect=effect))
        node.taints = tuple(taints)
        self._handle("update", "Node", node)
        return f"node/{name} tainted\n"

    # ---------------------------------------------------------------- label
    def _cmd_label(self, pos, flags):
        if len(pos) < 3:
            raise KubectlError("usage: label <kind> <name> key=value | key-")
        kind = resolve_kind(pos[0], self.api)
        ns = (self._ns(flags) or "default") if kind not in _CLUSTER_SCOPED else ""
        obj = copy.copy(self._get_required(kind, ns, pos[1]))
        if not hasattr(obj, "labels"):
            raise KubectlError(f"{resource_of(kind)} have no labels")
        labels = dict(obj.labels)
        for spec in pos[2:]:
            if spec.endswith("-"):
                labels.pop(spec[:-1], None)
            else:
                if "=" not in spec:
                    raise KubectlError(f"invalid label spec {spec!r}")
                k, v = spec.split("=", 1)
                if k in labels and labels[k] != v and not flags.get("overwrite"):
                    raise KubectlError(
                        f"'{k}' already has a value ({labels[k]}); use --overwrite")
                labels[k] = v
        obj.labels = labels
        self._handle("update", kind, obj)
        return f"{_singular(resource_of(kind))}/{pos[1]} labeled\n"

    # ------------------------------------------------------------------ top
    def _cmd_top(self, pos, flags):
        """`top nodes` / `top pods` from the scheduling surface: requested
        resources (there is no metrics-server; requests are the deterministic
        analog the scheduler itself reasons about)."""
        if not pos:
            raise KubectlError("top needs `nodes` or `pods`")
        what = resolve_kind(pos[0], self.api)
        store = self.api.store
        if what == "Node":
            used: Dict[str, Dict[str, int]] = {}
            for p in store.list_pods():
                if p.node_name:
                    u = used.setdefault(p.node_name, {})
                    for r, q in p.requests.items():
                        u[r] = u.get(r, 0) + q
            rows = []
            for n in sorted(store.list_nodes(), key=lambda n: n.name):
                u = used.get(n.name, {})
                cpu, mem = u.get("cpu", 0), u.get("memory", 0)
                ca, ma = n.allocatable.get("cpu", 0), n.allocatable.get("memory", 0)
                rows.append([
                    n.name, cpu, f"{100 * cpu // ca if ca else 0}%",
                    mem, f"{100 * mem // ma if ma else 0}%",
                ])
            return _fmt_table(["NAME", "CPU(req)", "CPU%", "MEMORY(req)", "MEMORY%"], rows)
        if what == "Pod":
            ns = self._ns(flags)
            rows = [[p.name, p.requests.get("cpu", 0), p.requests.get("memory", 0)]
                    for p in sorted(store.list_pods(), key=lambda p: p.name)
                    if ns is None or p.namespace == ns]
            return _fmt_table(["NAME", "CPU(req)", "MEMORY(req)"], rows)
        raise KubectlError("top supports `nodes` and `pods`")

    # -------------------------------------------------------------- rollout
    def _cmd_rollout(self, pos, flags):
        if len(pos) < 2 or pos[0] != "status":
            raise KubectlError("usage: rollout status deployment/<name>")
        if "/" in pos[1]:
            kw, name = pos[1].split("/", 1)
        elif len(pos) >= 3:
            kw, name = pos[1], pos[2]
        else:
            raise KubectlError("usage: rollout status deployment/<name>")
        if resolve_kind(kw, self.api) != "Deployment":
            raise KubectlError("rollout status supports deployments")
        ns = self._ns(flags) or "default"
        d = self._get_required("Deployment", ns, name)
        store = self.api.store
        owned = [rs for rs in store.list_objects("ReplicaSet")
                 if any(ref.uid == d.uid for ref in rs.owner_references)]
        ready = sum(rs.ready_replicas for rs in owned)
        if ready >= d.replicas and all(
            rs.ready_replicas >= rs.replicas for rs in owned
        ):
            return f'deployment "{name}" successfully rolled out\n'
        return (f"Waiting for deployment {name!r} rollout to finish: "
                f"{ready} of {d.replicas} updated replicas are available...\n")

    # -------------------------------------------------------- api-resources
    def _cmd_api_resources(self, pos, flags):
        shortnames: Dict[str, List[str]] = {}
        for w, k in _KIND_WORDS.items():
            if len(w) <= 6 and w != resource_of(k) and not w.endswith("s"):
                shortnames.setdefault(k, []).append(w)
        rows = []
        for kind in sorted(set(_KIND_WORDS.values())):
            rows.append([resource_of(kind), ",".join(sorted(shortnames.get(kind, []))),
                         "false" if kind in _CLUSTER_SCOPED else "true", kind])
        return _fmt_table(["NAME", "SHORTNAMES", "NAMESPACED", "KIND"], rows)

    # ------------------------------------------------------------ auth can-i
    def _cmd_auth(self, pos, flags):
        if len(pos) < 3 or pos[0] != "can-i":
            raise KubectlError("usage: auth can-i <verb> <resource>")
        user = self.api.authn.authenticate(self.token)
        if user is None:
            raise KubectlError("Error from server: invalid or missing bearer token")
        verb, res = pos[1], pos[2]
        try:
            res = resource_of(resolve_kind(res, self.api))
        except KubectlError:
            pass  # raw resource word
        ns = flags.get("namespace", "")
        ok = self.api.authz.authorize(user, verb, res, ns, "")
        return ("yes" if ok else "no") + "\n"

    # ---------------------------------------------------------------- events
    def _cmd_events(self, pos, flags):
        # Event API objects first (what the scheduler's recorder publishes);
        # a raw recorder is the fallback for recorder-only wiring
        ns = self._ns(flags)
        objs = list(self._handle("list", "Event", namespace=ns or ""))
        if ns is not None:
            objs = [e for e in objs if e.namespace == ns]
        if objs:
            return self._table("Event", objs)
        if self._handle("list", "Event") or self.recorder is None:
            # Event objects exist, just none in the requested namespace —
            # the raw recorder has no namespace filter, don't dump it all
            return "No events.\n"
        rows = [[e.reason, e.pod, e.node or "", e.message]
                for e in self.recorder.events[-200:]]
        return _fmt_table(["REASON", "OBJECT", "NODE", "MESSAGE"], rows)

    def _cmd_version(self, pos, flags):
        from . import __version__

        return f"kubernetes_tpu kubectl {__version__}\n"


# --------------------------------------------------------------- standalone


def make_admin_kubectl(store: Optional[ClusterStore] = None,
                       recorder: Optional[EventRecorder] = None) -> Kubectl:
    """An APIServer + admin token + Kubectl around a (new or given) store —
    the "kubeconfig with cluster-admin" of the in-process world."""
    from .scheduler.auth import TokenAuthenticator

    store = store or ClusterStore()
    authn = TokenAuthenticator()
    authn.add_token("admin-token", "admin", groups=("system:masters",))
    api = APIServer(store, authenticator=authn)
    return Kubectl(api, "admin-token", recorder=recorder)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    manifests = None
    if argv[:1] == ["--manifests"]:
        manifests = argv[1]
        argv = argv[2:]
    kc = make_admin_kubectl()
    if manifests:
        with open(manifests) as fh:
            for obj in ser.load_yaml(fh.read()):
                kc.api.handle(kc.token, "create", _store_kind(obj), obj)
    try:
        sys.stdout.write(kc.run(argv))
        return 0
    except KubectlError as e:
        sys.stderr.write(f"error: {e}\n")
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
